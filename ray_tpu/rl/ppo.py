"""PPO learner (reference role: rllib/algorithms/ppo — clipped surrogate,
GAE, entropy bonus), jax-native: the whole update (minibatch epochs
included) is one jitted function.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax


@dataclasses.dataclass(frozen=True)
class PPOConfig:
    hidden: Tuple[int, ...] = (64, 64)
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_epochs: int = 4
    num_minibatches: int = 4
    max_grad_norm: float = 0.5


class Rollout(NamedTuple):
    obs: jax.Array        # [T, N, obs_dim]
    actions: jax.Array    # [T, N]
    log_probs: jax.Array  # [T, N]
    rewards: jax.Array    # [T, N]
    dones: jax.Array      # [T, N]
    values: jax.Array     # [T+1, N]


def init_policy(key, obs_dim: int, num_actions: int, hidden) -> Dict:
    """Separate policy/value MLP towers, orthogonal-ish init."""
    params = {}
    for tower, out_dim in (("pi", max(num_actions, 1)), ("vf", 1)):
        sizes = (obs_dim,) + tuple(hidden) + (out_dim,)
        layers = []
        for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
            key, k = jax.random.split(key)
            scale = 0.01 if i == len(sizes) - 2 else jnp.sqrt(2.0 / a)
            layers.append({
                "w": jax.random.normal(k, (a, b)) * scale,
                "b": jnp.zeros((b,)),
            })
        params[tower] = layers
    return params


def _mlp(layers, x):
    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1:
            x = jnp.tanh(x)
    return x


def policy_logits(params, obs):
    return _mlp(params["pi"], obs)


def value_fn(params, obs):
    return _mlp(params["vf"], obs)[..., 0]


def gae_advantages(rewards, dones, values, gamma, lam):
    """values: [T+1, N]; returns (advantages [T,N], targets [T,N])."""
    def scan_fn(carry, inp):
        r, d, v, v_next = inp
        nonterm = 1.0 - d.astype(jnp.float32)
        delta = r + gamma * v_next * nonterm - v
        adv = delta + gamma * lam * nonterm * carry
        return adv, adv

    _, advs = lax.scan(
        scan_fn, jnp.zeros_like(rewards[0]),
        (rewards, dones, values[:-1], values[1:]), reverse=True)
    return advs, advs + values[:-1]


class PPOLearner:
    """Owns params + optimizer; jitted update over a Rollout."""

    def __init__(self, env, config: PPOConfig = PPOConfig(), seed: int = 0):
        self.env = env
        self.config = config
        key = jax.random.PRNGKey(seed)
        self.params = init_policy(
            key, env.obs_dim, env.num_actions, config.hidden)
        self.opt = optax.chain(
            optax.clip_by_global_norm(config.max_grad_norm),
            optax.adam(config.lr))
        self.opt_state = self.opt.init(self.params)
        self._update = jax.jit(self._make_update())

    def _make_update(self):
        cfg = self.config

        def loss_fn(params, batch):
            obs, actions, old_logp, advs, targets = batch
            logits = policy_logits(params, obs)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, actions[..., None].astype(jnp.int32), -1)[..., 0]
            ratio = jnp.exp(logp - old_logp)
            advs_n = (advs - advs.mean()) / (advs.std() + 1e-8)
            pg = -jnp.minimum(
                ratio * advs_n,
                jnp.clip(ratio, 1 - cfg.clip, 1 + cfg.clip) * advs_n).mean()
            v = value_fn(params, obs)
            vf = jnp.mean((v - targets) ** 2)
            ent = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = pg + cfg.vf_coeff * vf - cfg.entropy_coeff * ent
            return total, (pg, vf, ent)

        def update(params, opt_state, rollout: Rollout, key):
            advs, targets = gae_advantages(
                rollout.rewards, rollout.dones, rollout.values,
                cfg.gamma, cfg.gae_lambda)
            T, N = rollout.actions.shape
            flat = (
                rollout.obs.reshape(T * N, -1),
                rollout.actions.reshape(T * N),
                rollout.log_probs.reshape(T * N),
                advs.reshape(T * N),
                targets.reshape(T * N),
            )
            B = T * N
            mb = B // cfg.num_minibatches

            def epoch(carry, ekey):
                params, opt_state = carry
                perm = jax.random.permutation(ekey, B)

                def minibatch(carry, i):
                    params, opt_state = carry
                    idx = lax.dynamic_slice_in_dim(perm, i * mb, mb)
                    batch = tuple(x[idx] for x in flat)
                    (l, aux), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(params, batch)
                    updates, opt_state = self.opt.update(
                        grads, opt_state, params)
                    params = optax.apply_updates(params, updates)
                    return (params, opt_state), l

                (params, opt_state), losses = lax.scan(
                    minibatch, (params, opt_state),
                    jnp.arange(cfg.num_minibatches))
                return (params, opt_state), losses.mean()

            (params, opt_state), losses = lax.scan(
                epoch, (params, opt_state),
                jax.random.split(key, cfg.num_epochs))
            return params, opt_state, losses.mean()

        return update

    def update(self, rollout: Rollout, key) -> float:
        self.params, self.opt_state, loss = self._update(
            self.params, self.opt_state, rollout, key)
        return float(loss)

    def get_weights(self):
        return self.params

    def set_weights(self, params):
        self.params = params
