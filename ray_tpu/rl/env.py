"""Jax-native environments (reference role: rllib env/ + gymnasium).

A JaxEnv is a pair of pure functions (reset, step) over an explicit state
pytree — vmap gives vectorization, jit+scan gives whole-rollout fusion on
TPU. Classic-control dynamics (CartPole, Pendulum) are implemented from
their standard physics equations.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class JaxEnv:
    """reset(key) -> (state, obs); step(state, action, key) ->
    (state, obs, reward, done)."""

    reset: Callable[[jax.Array], Tuple[Any, jax.Array]]
    step: Callable[[Any, jax.Array, jax.Array], Tuple[Any, jax.Array,
                                                      jax.Array, jax.Array]]
    obs_dim: int
    num_actions: int  # 0 => continuous (action_dim = abs value)
    max_episode_steps: int


def CartPole(max_episode_steps: int = 500) -> JaxEnv:
    """CartPole-v1 dynamics (pole-balancing; standard constants)."""
    gravity = 9.8
    masscart, masspole = 1.0, 0.1
    total_mass = masscart + masspole
    length = 0.5
    polemass_length = masspole * length
    force_mag = 10.0
    tau = 0.02
    theta_lim = 12 * 2 * jnp.pi / 360
    x_lim = 2.4

    def reset(key):
        s = jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)
        t = jnp.zeros((), jnp.int32)
        return (s, t), s

    def step(state, action, key):
        s, t = state
        x, x_dot, theta, theta_dot = s
        force = jnp.where(action == 1, force_mag, -force_mag)
        costheta, sintheta = jnp.cos(theta), jnp.sin(theta)
        temp = (force + polemass_length * theta_dot**2 * sintheta
                ) / total_mass
        thetaacc = (gravity * sintheta - costheta * temp) / (
            length * (4.0 / 3.0 - masspole * costheta**2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + tau * x_dot
        x_dot = x_dot + tau * xacc
        theta = theta + tau * theta_dot
        theta_dot = theta_dot + tau * thetaacc
        s2 = jnp.stack([x, x_dot, theta, theta_dot])
        t2 = t + 1
        done = ((jnp.abs(x) > x_lim) | (jnp.abs(theta) > theta_lim)
                | (t2 >= max_episode_steps))
        # Auto-reset on done (vectorized-env semantics).
        (s_reset, t_reset), _ = reset(key)
        s_next = jnp.where(done, s_reset, s2)
        t_next = jnp.where(done, t_reset, t2)
        return (s_next, t_next), s_next, jnp.ones(()), done

    return JaxEnv(reset=reset, step=step, obs_dim=4, num_actions=2,
                  max_episode_steps=max_episode_steps)


def Pendulum(max_episode_steps: int = 200) -> JaxEnv:
    """Pendulum-v1 dynamics (continuous torque control)."""
    max_speed, max_torque = 8.0, 2.0
    dt, g, m, l = 0.05, 10.0, 1.0, 1.0

    def obs_of(s):
        th, thdot = s
        return jnp.stack([jnp.cos(th), jnp.sin(th), thdot])

    def reset(key):
        k1, k2 = jax.random.split(key)
        th = jax.random.uniform(k1, minval=-jnp.pi, maxval=jnp.pi)
        thdot = jax.random.uniform(k2, minval=-1.0, maxval=1.0)
        s = jnp.stack([th, thdot])
        t = jnp.zeros((), jnp.int32)
        return (s, t), obs_of(s)

    def step(state, action, key):
        s, t = state
        th, thdot = s
        u = jnp.clip(action.reshape(()), -max_torque, max_torque)
        angle = ((th + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        cost = angle**2 + 0.1 * thdot**2 + 0.001 * u**2
        thdot2 = jnp.clip(
            thdot + (3 * g / (2 * l) * jnp.sin(th)
                     + 3.0 / (m * l**2) * u) * dt,
            -max_speed, max_speed)
        th2 = th + thdot2 * dt
        s2 = jnp.stack([th2, thdot2])
        t2 = t + 1
        done = t2 >= max_episode_steps
        (s_reset, t_reset), _ = reset(key)
        s_next = jnp.where(done, s_reset, s2)
        t_next = jnp.where(done, t_reset, t2)
        return (s_next, t_next), obs_of(s_next), -cost, done

    return JaxEnv(reset=reset, step=step, obs_dim=3, num_actions=0,
                  max_episode_steps=max_episode_steps)


def gym_adapter(env_name: str, **kw) -> JaxEnv:
    """Wrap a gymnasium env id when the dynamics aren't jax-native.

    Host-loop fallback — steps run via io_callback, so rollouts are not
    fused; prefer the jax-native envs for throughput.
    """
    raise NotImplementedError(
        "gymnasium adapter lands with the host-executor escape hatch; use "
        "jax-native envs (CartPole/Pendulum) or implement JaxEnv directly")
