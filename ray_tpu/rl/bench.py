"""RL rollout benchmark (BASELINE.json config #5: PPO rollout collection,
CartPole-v1, 64 vectorized envs)."""

from __future__ import annotations

import time

import jax


def rollout_throughput(num_envs: int = 64, rollout_len: int = 512,
                       n_iters: int = 5) -> dict:
    from ray_tpu.rl.env import CartPole
    from ray_tpu.rl.env_runner import EnvRunner
    from ray_tpu.rl.ppo import PPOLearner

    env = CartPole()
    learner = PPOLearner(env)
    runner = EnvRunner(env, num_envs=num_envs, rollout_len=rollout_len)
    params = learner.get_weights()
    # Warmup/compile.
    ro = runner.sample(params)
    jax.block_until_ready(ro.rewards)
    t0 = time.perf_counter()
    for _ in range(n_iters):
        ro = runner.sample(params)
    jax.block_until_ready(ro.rewards)
    dt = (time.perf_counter() - t0) / n_iters
    steps = runner.steps_per_sample()
    return {
        "suite": "rl_rollout",
        "env_steps_per_sec": steps / dt,
        "num_envs": num_envs,
        "rollout_len": rollout_len,
        "wall_s_per_rollout": dt,
    }
