"""Simulated multi-node cluster (reference role: python/ray/cluster_utils.py
— the fixture nearly every distributed test runs on: multiple node stacks in
one process, nodes killable mid-test).

Each SimNode owns a ResourcePool + LocalScheduler (sharing the process
object store — object *placement* is tracked logically per node so node
loss can invalidate objects). The ClusterScheduler implements the
reference's node-selection policies: hybrid (pack until a utilization
threshold, then least-utilized), SPREAD, node affinity, and placement-group
bundle routing — and lineage-based object reconstruction when a node's
objects are lost (ObjectRecoveryManager parity).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.ids import NodeID
from ray_tpu._private.scheduler import LocalScheduler, ResourcePool, TaskSpec
from ray_tpu._private.worker import auto_init
from ray_tpu.exceptions import ObjectLostError, WorkerCrashedError
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)


class SimNode:
    def __init__(self, cluster: "Cluster", resources: Dict[str, float],
                 worker):
        self.node_id = NodeID.from_random()
        self.alive = True
        self.resource_pool = ResourcePool(resources)
        self.scheduler = LocalScheduler(
            worker.store, self.resource_pool,
            num_workers=max(int(resources.get("CPU", 1)), 1),
            task_events=worker.task_events,
            lineage=cluster.lineage,
            worker_pool=worker.worker_pool, shm_store=worker.shm_store)
        self.cluster = cluster

    def hex(self) -> str:
        return self.node_id.hex()

    def __repr__(self):
        state = "ALIVE" if self.alive else "DEAD"
        return f"SimNode({self.hex()[:8]}…, {state})"


class Cluster:
    """Multi-node simulation; becomes the worker's task router on connect."""

    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        self.lineage: Dict[Any, TaskSpec] = {}
        self._lock = threading.Lock()
        self.nodes: List[SimNode] = []
        self._task_node: Dict[Any, SimNode] = {}   # task_id -> node
        self._object_node: Dict[Any, SimNode] = {}  # object_id -> node
        self._recovering: set = set()
        self.worker = auto_init()
        self.worker.cluster = self
        if initialize_head:
            self.add_node(**(head_node_args or {"num_cpus": 2}))

    # --------------------------------------------------------------- nodes
    def add_node(self, num_cpus: int = 2,
                 resources: Optional[Dict[str, float]] = None,
                 **_kw) -> SimNode:
        total = {"CPU": float(num_cpus)}
        total.update(resources or {})
        node = SimNode(self, total, self.worker)
        with self._lock:
            self.nodes.append(node)
        return node

    @property
    def head_node(self) -> SimNode:
        return self.nodes[0]

    def remove_node(self, node: SimNode, lose_objects: bool = True):
        """Kill a node: running tasks fail (retriable ones resubmit
        elsewhere); optionally its objects become lost, to be reconstructed
        from lineage on next access."""
        node.alive = False
        with self._lock:
            if node in self.nodes:
                self.nodes.remove(node)
        # Fail/retry tasks currently on that node.
        running = list(node.scheduler._running.keys())
        queued = node.scheduler.queued_specs()
        node.scheduler.shutdown()
        for spec in queued:
            self._resubmit_or_fail(spec)
        for task_id in running:
            spec = self.lineage.get(task_id)
            if spec is not None:
                self._resubmit_or_fail(spec)
        if lose_objects:
            with self._lock:
                lost = [oid for oid, n in self._object_node.items()
                        if n is node]
                for oid in lost:
                    del self._object_node[oid]
            for oid in lost:
                self.worker.store.mark_lost(oid)

    def _resubmit_or_fail(self, spec: TaskSpec):
        if spec.attempt < spec.max_retries:
            retry = TaskSpec(
                task_id=spec.task_id, function=spec.function,
                args=spec.args, kwargs=spec.kwargs,
                num_returns=spec.num_returns, return_ids=spec.return_ids,
                name=spec.name, resources=spec.resources,
                max_retries=spec.max_retries,
                retry_exceptions=spec.retry_exceptions,
                scheduling_strategy=spec.scheduling_strategy,
                trace=spec.trace,
                attempt=spec.attempt + 1)
            self.submit(retry)
        else:
            err = WorkerCrashedError(
                f"node died while running task {spec.name!r}")
            for oid in spec.return_ids:
                self.worker.store.put_error(oid, err)

    # ---------------------------------------------------------- scheduling
    def submit(self, spec: TaskSpec):
        # Reconstruct lost dependencies first — the dep-wait machinery only
        # fires on put(), which for a lost object requires re-execution.
        from ray_tpu._private.scheduler import _collect_refs

        for dep in _collect_refs(spec.args, spec.kwargs):
            if self.worker.store.is_lost(dep.object_id):
                if self.recover_object(dep.object_id):
                    self.worker.store.clear_lost(dep.object_id)
        node = self._choose_node(spec)
        with self._lock:
            self._task_node[spec.task_id] = node
            for oid in spec.return_ids:
                self._object_node[oid] = node
        node.scheduler.submit(spec)

    def _choose_node(self, spec: TaskSpec) -> SimNode:
        with self._lock:
            alive = [n for n in self.nodes if n.alive]
        if not alive:
            raise RuntimeError("no alive nodes in cluster")
        strat = spec.scheduling_strategy
        if isinstance(strat, NodeAffinitySchedulingStrategy):
            for n in alive:
                if n.hex() == strat.node_id:
                    return n
            if not strat.soft:
                raise RuntimeError(
                    f"node {strat.node_id[:8]}… not alive (hard affinity)")
        if isinstance(strat, PlacementGroupSchedulingStrategy):
            pg = strat.placement_group
            idx = strat.placement_group_bundle_index
            idx = 0 if idx is None or idx < 0 else idx
            target_hex = pg.bundle_nodes[idx]
            for n in alive:
                if n.hex() == target_hex:
                    return n
            raise RuntimeError("placement group bundle node is gone")
        feasible = [n for n in alive if n.resource_pool.fits(spec.resources)]
        if not feasible:
            raise RuntimeError(
                f"no node can ever satisfy {spec.resources} "
                f"(infeasible demand)")
        def load(n: SimNode) -> float:
            # Acquired resources + queued demand: choose-time decisions must
            # see tasks that are queued but not yet dispatched, or a burst
            # of submissions all packs onto one node.
            cpus = max(n.resource_pool.total.get("CPU", 1.0), 1.0)
            return (n.resource_pool.utilization()
                    + n.scheduler.backlog_size() / cpus)

        if strat == "SPREAD":
            return min(feasible, key=load)
        # Hybrid default: pack onto the first node below the spread
        # threshold (reference scheduler_spread_threshold=0.5), else spread
        # by least load.
        threshold = GlobalConfig.scheduler_spread_threshold
        for n in feasible:
            if load(n) < threshold:
                return n
        return min(feasible, key=load)

    # ------------------------------------------------------- object recovery
    def recover_object(self, object_id) -> bool:
        """Lineage reconstruction: re-execute the producing task (and,
        transitively, producers of its lost args)."""
        spec = self.lineage.get(object_id.task_id())
        if spec is None:
            return False
        with self._lock:
            if object_id in self._recovering:
                return True
            self._recovering.add(object_id)
        try:
            from ray_tpu._private.scheduler import _collect_refs

            for dep in _collect_refs(spec.args, spec.kwargs):
                if not self.worker.store.is_ready(dep.object_id):
                    self.recover_object(dep.object_id)
            retry = TaskSpec(
                task_id=spec.task_id, function=spec.function,
                args=spec.args, kwargs=spec.kwargs,
                num_returns=spec.num_returns, return_ids=spec.return_ids,
                name=spec.name, resources=spec.resources,
                max_retries=spec.max_retries,
                retry_exceptions=spec.retry_exceptions,
                scheduling_strategy=spec.scheduling_strategy,
                trace=spec.trace,
                attempt=spec.attempt)
            self.submit(retry)
            return True
        finally:
            with self._lock:
                self._recovering.discard(object_id)

    # ------------------------------------------------------ placement groups
    def reserve_placement_group(self, pg):
        """Map bundles to nodes per strategy and reserve resources."""
        with self._lock:
            alive = [n for n in self.nodes if n.alive]
        strategy = pg.strategy
        placed: List[SimNode] = []
        acquired: List[Dict[str, float]] = []

        def rollback():
            for n, res in zip(placed, acquired):
                n.resource_pool.release(res)

        for i, bundle in enumerate(pg.bundles):
            candidates = list(alive)
            if strategy in ("PACK", "STRICT_PACK") and placed:
                candidates = [placed[0]] + [
                    n for n in candidates if n is not placed[0]]
                if strategy == "STRICT_PACK":
                    candidates = [placed[0]]
            if strategy == "STRICT_SPREAD":
                candidates = [n for n in candidates if n not in placed]
            chosen = None
            for n in candidates:
                if n.resource_pool.try_acquire(bundle):
                    chosen = n
                    break
            if chosen is None:
                rollback()
                raise ValueError(
                    f"cannot place bundle {i} {bundle} with strategy "
                    f"{strategy}")
            placed.append(chosen)
            acquired.append(bundle)
            pg.bundle_nodes[i] = chosen.hex()
        pg._cluster_reserved = list(zip(placed, acquired))
        pg._ready.set()

    def release_placement_group(self, pg):
        for node, res in getattr(pg, "_cluster_reserved", []):
            node.resource_pool.release(res)

    # -------------------------------------------------------------- teardown
    def shutdown(self):
        for node in list(self.nodes):
            node.scheduler.shutdown()
        self.nodes.clear()
        if getattr(self.worker, "cluster", None) is self:
            self.worker.cluster = None
