"""In-program collectives: jax.lax aliases bound to mesh axis names.

Use inside jit/shard_map; XLA lowers these to ICI collectives on TPU.
Mirrors the reference's op surface (allreduce/allgather/reducescatter/
broadcast/send-recv → psum/all_gather/psum_scatter/ppermute).
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]


def allreduce(x, axis: AxisName, op: str = "sum"):
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op!r}")


def allgather(x, axis: AxisName, *, tiled: bool = True, gather_axis: int = 0):
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reducescatter(x, axis: AxisName, *, scatter_axis: int = 0,
                  tiled: bool = True):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis,
                            tiled=tiled)


def broadcast(x, axis: str, root: int = 0):
    """Every shard gets the root shard's value (mask + psum: ppermute
    forbids duplicated sources)."""
    idx = lax.axis_index(axis)
    return lax.psum(jnp.where(idx == root, x, jnp.zeros_like(x)), axis)


def permute(x, axis: str, perm):
    return lax.ppermute(x, axis, perm)


def all_to_all(x, axis: str, split_axis: int, concat_axis: int,
               *, tiled: bool = True):
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def send_recv(x, axis: str, src: int, dst: int):
    """Point-to-point: dst receives src's value; everyone else keeps zeros
    (ppermute semantics — the aDAG NCCL p2p analogue in-program)."""
    return lax.ppermute(x, axis, [(src, dst)])


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return lax.axis_size(axis)
