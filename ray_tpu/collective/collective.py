"""Actor-plane collective groups (reference API-shape parity:
init_collective_group / declare / allreduce between actors).

Out-of-program collectives between ray_tpu actors, store-based: every rank
posts its contribution to the driver's internal KV (the GCS-KV analogue)
under a ``(group, round, rank)`` key, polls for the full round, and combines
locally — the same rendezvous shape as a gloo/TCP-store backend, which is
what makes it work identically for in-driver (thread) actors and
process-isolated actors, whose KV calls ride the per-worker API channel.
This is the control-plane analogue of the reference's Gloo backend — the
data plane for tensors should use in-program collectives
(ray_tpu.collective.ops) which ride ICI.

Round keys are garbage-collected with a two-round lag: a rank entering
round ``r`` has necessarily finished reading round ``r-1``, so each rank
deletes its own ``r-2`` key on completing ``r`` — no coordination needed.
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

_REDUCERS = {
    "sum": lambda arrs: np.sum(arrs, axis=0),
    "mean": lambda arrs: np.mean(arrs, axis=0),
    "max": lambda arrs: np.max(arrs, axis=0),
    "min": lambda arrs: np.min(arrs, axis=0),
    "product": lambda arrs: np.prod(arrs, axis=0),
}

_DEFAULT_TIMEOUT = 60.0
_POLL_S = 0.005

# Per-process state: which rank this thread holds in each group, and the
# per-(group, rank) round counters. One actor = one thread (or one
# process), so thread identity disambiguates multiple in-driver actors.
_rank_of: Dict[tuple, int] = {}  # (group, thread-ident) -> rank
_seq: Dict[tuple, int] = {}      # (group, rank) -> collective round
_p2p_send: Dict[tuple, int] = {}  # (group, src, dst) -> send round
_p2p_recv: Dict[tuple, int] = {}  # (group, src, dst) -> recv round
_epoch_of: Dict[str, str] = {}    # group -> epoch this process joined
_lock = threading.Lock()


def _worker():
    from ray_tpu._private.worker import auto_init

    return auto_init()


def _meta_key(group: str) -> bytes:
    return f"col|{group}|meta".encode()


def _parse_meta(raw: bytes) -> tuple:
    """Meta value is 'world_size|epoch'. The epoch changes every time the
    group is (re)created, so a process-backed actor that survived a
    destroy + re-create cannot desync rounds: its stale counters reset on
    re-join, and its stale round keys live under the old epoch prefix."""
    text = raw.decode()
    if "|" in text:
        ws, epoch = text.split("|", 1)
        return int(ws), epoch
    return int(text), ""


def _group_epoch(group: str) -> str:
    with _lock:
        return _epoch_of.get(group, "")


def _round_key(group: str, seq: int, rank: int,
               epoch: Optional[str] = None) -> bytes:
    e = _group_epoch(group) if epoch is None else epoch
    return f"col|{group}|{e}|r{seq}|{rank}".encode()


def _p2p_key(group: str, src: int, dst: int, seq: int) -> bytes:
    return f"col|{group}|{_group_epoch(group)}|p2p|{src}|{dst}|{seq}" \
        .encode()


def init_collective_group(world_size: int, rank: int,
                          backend: str = "xla",
                          group_name: str = "default") -> None:
    """Join the calling worker to a named group (reference signature
    parity; backend is advisory — 'xla' here, vs 'nccl'/'gloo' there)."""
    import uuid

    w = _worker()
    existing = w.kv_get(_meta_key(group_name))
    if existing is None:
        w.kv_put(_meta_key(group_name),
                 f"{world_size}|{uuid.uuid4().hex[:8]}".encode(),
                 overwrite=False)
        existing = w.kv_get(_meta_key(group_name))
    ws, epoch = _parse_meta(existing)
    if ws != world_size:
        raise ValueError(
            f"group {group_name!r} exists with world_size "
            f"{ws} != {world_size}")
    with _lock:
        _rank_of[(group_name, threading.get_ident())] = rank
        if _epoch_of.get(group_name) != epoch:
            # The group was re-created since this process last joined:
            # stale round counters from the previous epoch must reset or
            # this rank posts round N while fresh ranks poll round 0.
            for k in [k for k in _seq if k[0] == group_name]:
                _seq.pop(k, None)
            for d in (_p2p_send, _p2p_recv):
                for k in [k for k in d if k[0] == group_name]:
                    d.pop(k, None)
            _epoch_of[group_name] = epoch
        _seq.setdefault((group_name, rank), 0)


def create_collective_group(actors: List[Any], world_size: int,
                            ranks: List[int],
                            backend: str = "xla",
                            group_name: str = "default") -> None:
    """Driver-side declaration (reference: declare_collective_group)."""
    if len(actors) != len(ranks):
        raise ValueError("actors and ranks must align")
    refs = [
        a._ray_tpu_collective_join.remote(world_size, r, backend, group_name)
        if hasattr(a, "_ray_tpu_collective_join")
        else _remote_join(a, world_size, r, backend, group_name)
        for a, r in zip(actors, ranks)
    ]
    import ray_tpu

    ray_tpu.get(refs)


def _remote_join(actor, world_size, rank, backend, group_name):
    # Fallback: call a conventional `collective_join` method if present.
    return actor.collective_join.remote(world_size, rank, backend, group_name)


def _my_rank(group_name: str) -> int:
    key = (group_name, threading.get_ident())
    with _lock:
        if key not in _rank_of:
            raise RuntimeError(
                f"caller has not joined group {group_name!r}; call "
                f"init_collective_group first")
        return _rank_of[key]


def _world_size(group_name: str) -> int:
    raw = _worker().kv_get(_meta_key(group_name))
    if raw is None:
        raise RuntimeError(f"no collective group {group_name!r}")
    return _parse_meta(raw)[0]


def get_rank(group_name: str = "default") -> int:
    return _my_rank(group_name)


def get_collective_group_size(group_name: str = "default") -> int:
    return _world_size(group_name)


def _collect(group_name: str, value, combine, timeout: float):
    """Store-based rendezvous: post own contribution, poll for the round,
    combine locally (deterministic across ranks)."""
    w = _worker()
    ws = _world_size(group_name)
    rank = _my_rank(group_name)
    with _lock:
        seq = _seq[(group_name, rank)]
        _seq[(group_name, rank)] = seq + 1
    own_key = _round_key(group_name, seq, rank)
    w.kv_put(own_key, pickle.dumps(value, protocol=5))
    vals: Dict[int, Any] = {}
    deadline = time.monotonic() + timeout
    while True:
        for r in range(ws):
            if r not in vals:
                raw = w.kv_get(_round_key(group_name, seq, r))
                if raw is not None:
                    vals[r] = pickle.loads(raw)
        if len(vals) == ws:
            break
        if time.monotonic() > deadline:
            # Withdraw so a later round can't complete against stale data,
            # and rewind the round counter for a clean retry.
            w.kv_del(own_key)
            with _lock:
                _seq[(group_name, rank)] = seq
            raise TimeoutError(
                f"collective on group {group_name!r}: only "
                f"{len(vals)}/{ws} ranks arrived within {timeout}s")
        time.sleep(_POLL_S)
    if seq >= 2:  # two-round-lag GC of this rank's own old key
        w.kv_del(_round_key(group_name, seq - 2, rank))
    return combine([vals[r] for r in range(ws)])


def allreduce(tensor, group_name: str = "default", op: str = "sum",
              timeout: float = _DEFAULT_TIMEOUT):
    out = _collect(group_name, np.asarray(tensor),
                   lambda vals: _REDUCERS[op](np.stack(vals)), timeout)
    return np.array(out, copy=True)


def allgather(tensor, group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT):
    out = _collect(group_name, np.asarray(tensor),
                   lambda vals: [np.array(v, copy=True) for v in vals],
                   timeout)
    return list(out)


def reducescatter(tensor, group_name: str = "default", op: str = "sum",
                  timeout: float = _DEFAULT_TIMEOUT):
    ws = _world_size(group_name)
    rank = _my_rank(group_name)
    arr = np.asarray(tensor)
    if arr.shape[0] % ws:
        raise ValueError(
            f"leading dim {arr.shape[0]} not divisible by world size {ws}")
    full = _collect(group_name, arr,
                    lambda vals: _REDUCERS[op](np.stack(vals)), timeout)
    chunk = full.shape[0] // ws
    return np.array(full[rank * chunk:(rank + 1) * chunk], copy=True)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT):
    out = _collect(group_name, np.asarray(tensor),
                   lambda vals: vals[src_rank], timeout)
    return np.array(out, copy=True)


def barrier(group_name: str = "default", timeout: float = _DEFAULT_TIMEOUT):
    _collect(group_name, None, lambda vals: None, timeout)


def send(tensor, dst_rank: int, group_name: str = "default"):
    w = _worker()
    src = _my_rank(group_name)
    with _lock:
        seq = _p2p_send.get((group_name, src, dst_rank), 0)
        _p2p_send[(group_name, src, dst_rank)] = seq + 1
    w.kv_put(_p2p_key(group_name, src, dst_rank, seq),
             pickle.dumps(np.array(np.asarray(tensor), copy=True),
                          protocol=5))


def recv(src_rank: int, group_name: str = "default",
         timeout: float = _DEFAULT_TIMEOUT):
    w = _worker()
    dst = _my_rank(group_name)
    with _lock:
        seq = _p2p_recv.get((group_name, src_rank, dst), 0)
        _p2p_recv[(group_name, src_rank, dst)] = seq + 1
    key = _p2p_key(group_name, src_rank, dst, seq)
    deadline = time.monotonic() + timeout
    while True:
        raw = w.kv_get(key)
        if raw is not None:
            w.kv_del(key)
            return pickle.loads(raw)
        if time.monotonic() > deadline:
            # Rewind so a retry (or the late-arriving message) still lines
            # up with this sequence number instead of skipping it forever.
            with _lock:
                _p2p_recv[(group_name, src_rank, dst)] = seq
            raise TimeoutError(f"recv({src_rank}->{dst}) timed out")
        time.sleep(_POLL_S)


def destroy_collective_group(group_name: str = "default") -> None:
    w = _worker()
    for key in w.kv_keys(f"col|{group_name}|".encode()):
        w.kv_del(key)
    with _lock:
        for k in [k for k in _rank_of if k[0] == group_name]:
            _rank_of.pop(k, None)
        for k in [k for k in _seq if k[0] == group_name]:
            _seq.pop(k, None)
        for d in (_p2p_send, _p2p_recv):
            for k in [k for k in d if k[0] == group_name]:
                d.pop(k, None)
        # A re-created group mints a fresh epoch; forgetting ours makes
        # the next init_collective_group adopt it and reset counters even
        # in OTHER processes (their cached epoch no longer matches).
        _epoch_of.pop(group_name, None)
