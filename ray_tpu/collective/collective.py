"""Actor-plane collective groups (reference API-shape parity:
init_collective_group / declare / allreduce between actors).

Out-of-program collectives between ray_tpu actors: a named group with
ranks, a rendezvous barrier, and CPU reductions over numpy arrays. This is
the control-plane analogue of the reference's Gloo backend — the data plane
for tensors should use in-program collectives (ray_tpu.collective.ops) which
ride ICI.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

_REDUCERS = {
    "sum": lambda arrs: np.sum(arrs, axis=0),
    "mean": lambda arrs: np.mean(arrs, axis=0),
    "max": lambda arrs: np.max(arrs, axis=0),
    "min": lambda arrs: np.min(arrs, axis=0),
    "product": lambda arrs: np.prod(arrs, axis=0),
}


class _Group:
    def __init__(self, world_size: int, name: str):
        self.world_size = world_size
        self.name = name
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._round = 0
        self._contrib: Dict[int, Any] = {}
        self._result: Any = None
        self._p2p: Dict[tuple, Any] = {}
        self._p2p_cv = threading.Condition()

    def _collect(self, rank: int, value, combine, timeout: float):
        """Rendezvous: all ranks contribute, one combines, all read."""
        with self._cv:
            my_round = self._round
            self._contrib[rank] = value
            if len(self._contrib) == self.world_size:
                vals = [self._contrib[r] for r in range(self.world_size)]
                self._result = combine(vals)
                self._contrib = {}
                self._round += 1
                self._cv.notify_all()
            else:
                if not self._cv.wait_for(
                        lambda: self._round > my_round, timeout=timeout):
                    arrived = len(self._contrib)
                    # Withdraw this rank's contribution (if the round has
                    # not advanced) so a later collective on the group
                    # doesn't complete early with a stale value.
                    if (self._round == my_round
                            and self._contrib.get(rank) is value):
                        del self._contrib[rank]
                    raise TimeoutError(
                        f"collective on group {self.name!r}: only "
                        f"{arrived}/{self.world_size} ranks "
                        f"arrived within {timeout}s")
            return self._result

    def send(self, value, src: int, dst: int):
        with self._p2p_cv:
            self._p2p[(src, dst)] = value
            self._p2p_cv.notify_all()

    def recv(self, src: int, dst: int, timeout: float):
        with self._p2p_cv:
            if not self._p2p_cv.wait_for(
                    lambda: (src, dst) in self._p2p, timeout=timeout):
                raise TimeoutError(f"recv({src}->{dst}) timed out")
            return self._p2p.pop((src, dst))


_groups: Dict[str, _Group] = {}
_rank_of: Dict[tuple, int] = {}  # (group, thread-key) -> rank
_lock = threading.Lock()
_DEFAULT_TIMEOUT = 60.0


def init_collective_group(world_size: int, rank: int,
                          backend: str = "xla",
                          group_name: str = "default") -> None:
    """Join the calling worker to a named group (reference signature
    parity; backend is advisory — 'xla' here, vs 'nccl'/'gloo' there)."""
    with _lock:
        g = _groups.get(group_name)
        if g is None:
            g = _Group(world_size, group_name)
            _groups[group_name] = g
        elif g.world_size != world_size:
            raise ValueError(
                f"group {group_name!r} exists with world_size "
                f"{g.world_size} != {world_size}")
    _set_rank(group_name, rank)


def create_collective_group(actors: List[Any], world_size: int,
                            ranks: List[int],
                            backend: str = "xla",
                            group_name: str = "default") -> None:
    """Driver-side declaration (reference: declare_collective_group)."""
    if len(actors) != len(ranks):
        raise ValueError("actors and ranks must align")
    refs = [
        a._ray_tpu_collective_join.remote(world_size, r, backend, group_name)
        if hasattr(a, "_ray_tpu_collective_join")
        else _remote_join(a, world_size, r, backend, group_name)
        for a, r in zip(actors, ranks)
    ]
    import ray_tpu

    ray_tpu.get(refs)


def _remote_join(actor, world_size, rank, backend, group_name):
    # Fallback: call a conventional `collective_join` method if present.
    return actor.collective_join.remote(world_size, rank, backend, group_name)


def _set_rank(group_name: str, rank: int):
    key = (group_name, threading.get_ident())
    with _lock:
        _rank_of[key] = rank


def _my_rank(group_name: str) -> int:
    key = (group_name, threading.get_ident())
    with _lock:
        if key not in _rank_of:
            raise RuntimeError(
                f"caller has not joined group {group_name!r}; call "
                f"init_collective_group first")
        return _rank_of[key]


def _group(group_name: str) -> _Group:
    with _lock:
        g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(f"no collective group {group_name!r}")
    return g


def get_rank(group_name: str = "default") -> int:
    return _my_rank(group_name)


def get_collective_group_size(group_name: str = "default") -> int:
    return _group(group_name).world_size


def allreduce(tensor, group_name: str = "default", op: str = "sum",
              timeout: float = _DEFAULT_TIMEOUT):
    g = _group(group_name)
    arr = np.asarray(tensor)
    out = g._collect(_my_rank(group_name), arr,
                     lambda vals: _REDUCERS[op](np.stack(vals)), timeout)
    return np.array(out, copy=True)


def allgather(tensor, group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT):
    g = _group(group_name)
    out = g._collect(_my_rank(group_name), np.asarray(tensor),
                     lambda vals: [np.array(v, copy=True) for v in vals],
                     timeout)
    return list(out)


def reducescatter(tensor, group_name: str = "default", op: str = "sum",
                  timeout: float = _DEFAULT_TIMEOUT):
    g = _group(group_name)
    rank = _my_rank(group_name)
    arr = np.asarray(tensor)
    if arr.shape[0] % g.world_size:
        raise ValueError(
            f"leading dim {arr.shape[0]} not divisible by world size "
            f"{g.world_size}")
    full = g._collect(rank, arr,
                      lambda vals: _REDUCERS[op](np.stack(vals)), timeout)
    chunk = full.shape[0] // g.world_size
    return np.array(full[rank * chunk:(rank + 1) * chunk], copy=True)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT):
    g = _group(group_name)
    out = g._collect(_my_rank(group_name), np.asarray(tensor),
                     lambda vals: vals[src_rank], timeout)
    return np.array(out, copy=True)


def barrier(group_name: str = "default", timeout: float = _DEFAULT_TIMEOUT):
    g = _group(group_name)
    g._collect(_my_rank(group_name), None, lambda vals: None, timeout)


def send(tensor, dst_rank: int, group_name: str = "default"):
    g = _group(group_name)
    g.send(np.array(np.asarray(tensor), copy=True),
           _my_rank(group_name), dst_rank)


def recv(src_rank: int, group_name: str = "default",
         timeout: float = _DEFAULT_TIMEOUT):
    g = _group(group_name)
    return g.recv(src_rank, _my_rank(group_name), timeout)


def destroy_collective_group(group_name: str = "default") -> None:
    with _lock:
        _groups.pop(group_name, None)
        for key in [k for k in _rank_of if k[0] == group_name]:
            _rank_of.pop(key, None)
