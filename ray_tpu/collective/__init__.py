"""Collective communication API (reference role: python/ray/util/collective).

The reference wraps NCCL/Gloo process groups created between actors
(init_collective_group / allreduce / ... [unverified]). TPU-native, there
are two planes:

- **In-program** (the fast path): collectives are XLA ops on mesh axes —
  ``ray_tpu.collective.allreduce(x, axis="dp")`` inside shard_map/jit
  compiles to an ICI collective. These are thin aliases over jax.lax so
  user code written against the reference API shape ports directly.
- **Out-of-program** (actor plane): named groups of actors exchanging host
  arrays, matching the reference's group management semantics
  (declare_collective_group, rank/world_size) with a CPU reduction — the
  control-plane analogue of its Gloo backend.
"""

from ray_tpu.collective.collective import (
    allgather,
    allreduce,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    get_rank,
    get_collective_group_size,
    init_collective_group,
    recv,
    reducescatter,
    send,
)
from ray_tpu.collective import ops

__all__ = [
    "allgather",
    "allreduce",
    "barrier",
    "broadcast",
    "create_collective_group",
    "destroy_collective_group",
    "get_rank",
    "get_collective_group_size",
    "init_collective_group",
    "ops",
    "recv",
    "reducescatter",
    "send",
]
