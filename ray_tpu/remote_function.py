"""@remote decorator for tasks.

Rebuild of the reference's remote function surface (reference:
python/ray/remote_function.py [unverified]): ``@remote`` wraps a function
into a handle whose ``.remote(...)`` submits a task and returns ObjectRef(s);
``.options(...)`` overrides per-call options (num_returns, resources,
max_retries, retry_exceptions, name, scheduling_strategy).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.scheduler import TaskSpec
from ray_tpu.runtime_env import coerce_runtime_env as _coerce_env

_OPTION_KEYS = frozenset({
    "num_returns", "num_cpus", "num_tpus", "num_gpus", "resources",
    "max_retries", "retry_exceptions", "name", "scheduling_strategy",
    "runtime_env", "max_calls", "memory", "_metadata", "accelerator_type",
    "label_selector",
})


def _normalize_resources(opts: Dict[str, Any]) -> Dict[str, float]:
    resources = dict(opts.get("resources") or {})
    num_cpus = opts.get("num_cpus")
    resources["CPU"] = float(1 if num_cpus is None else num_cpus)
    # Accept num_gpus as an alias for num_tpus so reference-style call sites
    # (`num_gpus=1`) map onto the TPU resource.
    num_acc = opts.get("num_tpus", opts.get("num_gpus"))
    if num_acc:
        resources["TPU"] = float(num_acc)
    return {k: v for k, v in resources.items() if v}


class RemoteFunction:
    def __init__(self, function: Callable, options: Dict[str, Any]):
        for k in options:
            if k not in _OPTION_KEYS:
                raise ValueError(f"unknown @remote option {k!r}")
        self._function = function
        self._options = options
        functools.update_wrapper(self, function)

    def options(self, **options) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(options)
        return RemoteFunction(self._function, merged)

    def remote(self, *args, **kwargs):
        from ray_tpu._private.worker import auto_init

        worker = auto_init()
        opts = self._options
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns == "streaming"
        if not streaming and not isinstance(num_returns, int):
            raise ValueError(
                f'num_returns must be an int or "streaming", '
                f'got {num_returns!r}')
        task_id = worker.next_task_id()
        if streaming:
            # Streaming generator: item refs materialize dynamically as
            # the task yields; the only statically-declared return is the
            # END MARKER object (total yield count / task error), which
            # rides the whole existing completion machinery.
            from ray_tpu._private.streaming import stream_end_id

            return_ids = [stream_end_id(task_id)]
        else:
            # num_returns=0 still gets one hidden completion marker object
            # so dependents/lineage/ref-release have something to hang off.
            return_ids = [
                ObjectID.for_task_return(task_id, i)
                for i in range(max(num_returns, 1))
            ]
        max_retries = opts.get("max_retries")
        if max_retries is None:
            max_retries = GlobalConfig.task_max_retries
        spec = TaskSpec(
            task_id=task_id,
            function=self._function,
            args=args,
            kwargs=kwargs,
            num_returns=1 if streaming else num_returns,
            return_ids=return_ids,
            name=opts.get("name") or getattr(
                self._function, "__name__", "task"),
            resources=_normalize_resources(opts),
            max_retries=max_retries,
            retry_exceptions=bool(opts.get("retry_exceptions", False)),
            scheduling_strategy=opts.get("scheduling_strategy"),
            runtime_env=_coerce_env(opts.get("runtime_env")),
            streaming=streaming,
            backpressure=(GlobalConfig.generator_backpressure_items
                          if streaming else 0),
        )
        refs = worker.submit_task(spec)
        if streaming:
            from ray_tpu._private.worker import ObjectRefGenerator

            return ObjectRefGenerator(task_id, worker)
        if num_returns == 0:
            return None
        return refs[0] if num_returns == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self.__name__!r} cannot be called directly; "
            f"use {self.__name__}.remote()."
        )

    def bind(self, *args, **kwargs):
        """DAG authoring: create a lazy FunctionNode (see ray_tpu.dag)."""
        from ray_tpu.dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs)


def remote(*args, **options):
    """``@remote`` / ``@remote(num_cpus=...)`` for functions and classes."""
    from ray_tpu.actor import ActorClass

    def _make(target):
        if isinstance(target, type):
            return ActorClass(target, options)
        if callable(target):
            return RemoteFunction(target, options)
        raise TypeError(f"@remote target must be function or class: {target}")

    if len(args) == 1 and not options and (
        callable(args[0]) or isinstance(args[0], type)
    ):
        return _make(args[0])
    if args:
        raise TypeError("@remote options must be keyword arguments")
    return _make


def method(**options):
    """``@method(num_returns=...)`` decorator for actor methods."""

    def _wrap(fn):
        fn.__ray_tpu_method_options__ = options
        return fn

    return _wrap
