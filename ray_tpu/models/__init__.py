"""Model zoo for the TPU-native framework.

The reference ships model code through RLlib modules and Train integrations
(torch); here the flagship is a jax-native decoder-only transformer wired
directly into the parallelism layer (dp/pp/tp/sp/ep over one Mesh).
"""

from ray_tpu.models.draft import draft_config, shift_params
from ray_tpu.models.transformer import (
    TransformerConfig,
    decode_step,
    forward,
    init_kv_cache,
    init_params,
    loss_fn,
    make_spmd_train_step,
    param_specs,
    prefill_chunk,
    prefill_with_cache,
    verify_step,
)

__all__ = [
    "TransformerConfig",
    "decode_step",
    "draft_config",
    "forward",
    "init_kv_cache",
    "init_params",
    "loss_fn",
    "make_spmd_train_step",
    "param_specs",
    "prefill_chunk",
    "prefill_with_cache",
    "shift_params",
    "verify_step",
]
