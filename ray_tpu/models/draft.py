"""Draft-model helpers for speculative decoding (reference role: the
draft/target pairing in speculative-decoding serving stacks — a small
cheap model proposes k tokens, the flagship verifies them in one
multi-token step; see ``llm/engine.py``'s spec-decode path).

Two pieces:

- ``draft_config``: derive a shrunk ``TransformerConfig`` from the
  flagship's (same vocab — proposals must be scoreable by the flagship
  — fewer layers, narrower residual stream). Any field can be pinned
  via overrides; divisibility (d_model % n_heads, n_heads % n_kv_heads)
  is the caller's contract, as with any TransformerConfig.
- ``shift_params``: a SYNTHETIC deterministic parameterization whose
  greedy next token is exactly ``(t + shift) % vocab_size`` for last
  token ``t``, on ANY config with ``d_model >= vocab_size``. Zero
  attention/MLP weights make every layer an identity residual update
  (zero q/k/v -> uniform softmax over zero values -> zero output; zero
  MLP -> zero), a one-hot embedding carries the token through the
  residual stream, and a shift-permutation lm_head reads it back out.
  Because the rule depends only on the last token — not on width or
  depth — a shift-params draft and a shift-params flagship agree
  token-for-token by construction: the deterministic ~1.0-acceptance
  workload the spec-decode bench and tests measure against (honestly
  disclosed as synthetic; real model pairs land wherever their
  distributional agreement puts them).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.models.transformer import TransformerConfig, init_params

__all__ = ["draft_config", "shift_params"]


def draft_config(base: TransformerConfig, **overrides
                 ) -> TransformerConfig:
    """A small draft config derived from the flagship's: same vocab and
    context window, half the depth/width by default (floored so tiny
    test configs stay valid). Overrides win field-by-field."""
    small: Dict[str, Any] = dict(
        n_layers=max(1, base.n_layers // 2),
        d_model=max(32, base.d_model // 2),
        n_heads=max(1, base.n_heads // 2),
        n_kv_heads=max(1, base.n_kv_heads // 2),
        d_ff=max(32, base.d_ff // 2),
    )
    small.update(overrides)
    return dataclasses.replace(base, **small)


def shift_params(cfg: TransformerConfig, shift: int = 1) -> Dict[str, Any]:
    """Parameters realizing greedy next == ``(last_token + shift) %
    vocab`` exactly (see module docstring). Requires ``d_model >=
    vocab_size`` so the one-hot embedding fits the residual stream."""
    if cfg.d_model < cfg.vocab_size:
        raise ValueError(
            f"shift_params needs d_model ({cfg.d_model}) >= vocab_size "
            f"({cfg.vocab_size}) for the one-hot embedding")
    params = init_params(cfg, jax.random.PRNGKey(0))
    # Zero every layer weight, keep every norm gain at one: each layer
    # becomes x -> x (attention output and MLP both exactly zero).
    layers = {}
    for name, arr in params["layers"].items():
        if name.endswith("norm"):
            layers[name] = jnp.ones_like(arr)
        else:
            layers[name] = jnp.zeros_like(arr)
    params["layers"] = layers
    # One-hot embed: token t -> e_t in the first vocab dims. final_norm
    # of ones rescales positively per row, preserving the argmax.
    embed = jnp.zeros((cfg.vocab_size, cfg.d_model), cfg.dtype)
    embed = embed.at[jnp.arange(cfg.vocab_size),
                     jnp.arange(cfg.vocab_size)].set(1.0)
    params["embed"] = embed
    params["final_norm"] = jnp.ones_like(params["final_norm"])
    # Shift-permutation readout: logits[v] = x[(v - shift) % vocab], so
    # the single positive residual dim t votes for (t + shift) % vocab.
    head = jnp.zeros((cfg.d_model, cfg.vocab_size), cfg.dtype)
    head = head.at[jnp.arange(cfg.vocab_size),
                   (jnp.arange(cfg.vocab_size) + shift)
                   % cfg.vocab_size].set(1.0)
    params["lm_head"] = head
    return params
