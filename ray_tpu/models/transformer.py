"""Flagship model: decoder-only transformer, TPU-first.

Design notes (vs the reference, which delegates all model math to torch —
SURVEY.md §2.6): everything here is built for the MXU and the Mesh:

- bfloat16 activations, f32 params/optimizer state; all FLOPs in batched
  einsums that tile onto the systolic array; static shapes throughout.
- Layers are **stacked** ([L, ...] leading axis) and run under ``lax.scan``
  → one compiled layer body regardless of depth, with optional
  ``jax.checkpoint`` rematerialisation for HBM.
- Two execution paths:
  1. ``forward`` / ``loss_fn``: GSPMD path — logical sharding constraints
     (ShardingRules) and jit; XLA inserts the dp/fsdp/tp collectives.
  2. ``make_spmd_train_step``: manual path — ``jax.shard_map`` over the
     full (dp, pp, tp, sp, ep) mesh with explicit collectives: Megatron
     column/row TP with psum, ring attention over sp, MoE all_to_all over
     ep, GPipe ppermute over pp, gradient psum-mean over dp. This is the
     multi-chip training step the driver dry-runs.

GQA attention with rotary embeddings, RMSNorm, SwiGLU MLP, optional MoE
layers every ``moe_every``-th layer.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import mesh_shape
from ray_tpu.parallel.moe import moe_dispatch_combine
from ray_tpu.parallel.ring_attention import ring_attention
from ray_tpu.parallel.sharding import ShardingRules


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8
    d_ff: int = 1376
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    # MoE: 0 = dense; otherwise every `moe_every`-th layer is MoE.
    num_experts: int = 0
    moe_every: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def _dense_init(key, shape, fan_in):
    return (jax.random.normal(key, shape, jnp.float32)
            * (1.0 / math.sqrt(fan_in)))


def init_params(cfg: TransformerConfig, key: jax.Array) -> Dict[str, Any]:
    """Stacked-layer param pytree. Weights f32 (master copy)."""
    D, F, Hd = cfg.d_model, cfg.d_ff, cfg.head_dim
    nq, nkv, L = cfg.n_heads, cfg.n_kv_heads, cfg.n_layers
    # One distinct key per weight family: same-shaped families (wq/wk/wv,
    # w_gate/w_up, e_gate/e_up) must not share init, or attention/MLP
    # branches start out identical and training silently degrades.
    ks = jax.random.split(key, 16)
    _next_family = iter(range(2, 16))

    def stack(initfn):
        keys = jax.random.split(ks[next(_next_family)], L)
        return jax.vmap(initfn)(keys)

    layers = {
        "attn_norm": jnp.ones((L, D), jnp.float32),
        "wq": stack(lambda k: _dense_init(k, (D, nq * Hd), D)),
        "wk": stack(lambda k: _dense_init(k, (D, nkv * Hd), D)),
        "wv": stack(lambda k: _dense_init(k, (D, nkv * Hd), D)),
        "wo": stack(lambda k: _dense_init(k, (nq * Hd, D), nq * Hd)),
        "mlp_norm": jnp.ones((L, D), jnp.float32),
        "w_gate": stack(lambda k: _dense_init(k, (D, F), D)),
        "w_up": stack(lambda k: _dense_init(k, (D, F), D)),
        "w_down": stack(lambda k: _dense_init(k, (F, D), F)),
    }
    if cfg.num_experts:
        E = cfg.num_experts
        layers["router"] = stack(lambda k: _dense_init(k, (D, E), D))
        layers["e_gate"] = stack(
            lambda k: _dense_init(k, (E, D, F), D))
        layers["e_up"] = stack(lambda k: _dense_init(k, (E, D, F), D))
        layers["e_down"] = stack(lambda k: _dense_init(k, (E, F, D), F))
    return {
        "embed": jax.random.normal(ks[0], (cfg.vocab_size, D),
                                   jnp.float32) * 0.02,
        "layers": layers,
        "final_norm": jnp.ones((D,), jnp.float32),
        "lm_head": _dense_init(ks[1], (D, cfg.vocab_size), D),
    }


def param_specs(cfg: TransformerConfig,
                rules: Optional[ShardingRules] = None) -> Dict[str, Any]:
    """PartitionSpec tree matching init_params (GSPMD path).

    Layer weights carry a leading stacked-layer axis: sharded on pp when a
    pipeline mesh is used (stages = contiguous layer blocks), else None.
    2D weights shard wide-axis on tp, narrow on fsdp (ZeRO-3).
    """
    r = rules or ShardingRules()
    st, tp, fs = r.stage, r.mlp, r.fsdp_shard
    layers = {
        "attn_norm": P(st, None),
        "wq": P(st, fs, tp), "wk": P(st, fs, tp), "wv": P(st, fs, tp),
        "wo": P(st, tp, fs),
        "mlp_norm": P(st, None),
        "w_gate": P(st, fs, tp), "w_up": P(st, fs, tp),
        "w_down": P(st, tp, fs),
    }
    if cfg.num_experts:
        layers.update({
            "router": P(st, None, None),
            "e_gate": P(st, r.expert, None, tp),
            "e_up": P(st, r.expert, None, tp),
            "e_down": P(st, r.expert, tp, None),
        })
    return {
        "embed": P(r.vocab, None),
        "layers": layers,
        "final_norm": P(None),
        "lm_head": P(fs, r.vocab),
    }


def rms_norm(x, w, eps=1e-6):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * lax.rsqrt(var + eps)).astype(x.dtype) * w.astype(x.dtype)


def rope(x, positions, theta):
    # x: [B, S, H, Dh]; rotate pairs (even, odd halves).
    Dh = x.shape[-1]
    half = Dh // 2
    freqs = jnp.exp(
        -jnp.arange(0, half, dtype=jnp.float32) * (math.log(theta) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B?, S, half]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], axis=-1)


def _attention_dense(q, k, v, causal=True, grad=True):
    """q [B,S,Hq,Dh], k/v [B,S,Hkv,Dh] -> [B,S,Hq,Dh].

    On TPU with tileable shapes this dispatches to the Pallas flash
    kernel (ops/flash_attention.py, differentiable via its blockwise
    custom_vjp) — the [S, S] score matrix never hits HBM, which is what
    unlocks long sequences and large batches under grad. The kernel's
    FA2 backward wants matched head counts, so GQA repeat-expands K/V
    only on the differentiable (``grad=True``, training) path;
    inference callers pass ``grad=False`` and take the GROUPED flash
    forward (``flash_attention_grouped``), whose K/V block specs
    index-map each query head to its kv group — no n_heads-wide K/V
    exists anywhere on the serving path. The dense einsum path keeps
    GQA GROUPED too: queries fold to [B, S, Hkv, group, Dh] and
    contract against K/V at n_kv_heads width (the same grouped form the
    paged decode cache relies on).
    """
    B, S, Hq, Dh = q.shape
    Hkv = k.shape[2]
    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    if on_tpu and S >= 128 and S % 128 == 0 and Dh % 8 == 0:
        if Hq != Hkv and not grad:
            from ray_tpu.ops.flash_attention import flash_attention_grouped

            o = flash_attention_grouped(q.transpose(0, 2, 1, 3),
                                        k.transpose(0, 2, 1, 3),
                                        v.transpose(0, 2, 1, 3),
                                        causal=causal)
            return o.transpose(0, 2, 1, 3)
        from ray_tpu.ops.flash_attention import flash_attention

        if Hq != Hkv:
            k = jnp.repeat(k, Hq // Hkv, axis=2)
            v = jnp.repeat(v, Hq // Hkv, axis=2)
        o = flash_attention(q.transpose(0, 2, 1, 3),
                            k.transpose(0, 2, 1, 3),
                            v.transpose(0, 2, 1, 3), causal=causal)
        return o.transpose(0, 2, 1, 3)
    group = Hq // Hkv
    qg = q.reshape(B, S, Hkv, group, Dh)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k) * (Dh ** -0.5)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None, None], s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    o = jnp.einsum("bhgqk,bkhd->bqhgd", p, v)
    return o.reshape(B, S, Hq, Dh)


def _project_qkv(cfg, lp, h, positions):
    """q/k/v projection + rope, shared by the training layer body and
    the cached prefill/decode paths. h [B, S, D] -> q [B,S,Hq,Dh],
    k/v [B,S,Hkv,Dh] (k/v at n_kv_heads width)."""
    dt = cfg.dtype
    B, S, _ = h.shape
    Hd = cfg.head_dim
    q = (h @ lp["wq"].astype(dt)).reshape(B, S, -1, Hd)
    k = (h @ lp["wk"].astype(dt)).reshape(B, S, -1, Hd)
    v = (h @ lp["wv"].astype(dt)).reshape(B, S, -1, Hd)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    return q, k, v


def _layer_fn(cfg: TransformerConfig, lp: Dict[str, jax.Array], x: jax.Array,
              positions: jax.Array, layer_idx: jax.Array,
              sp_axis: Optional[str] = None,
              ep_axis: Optional[str] = None,
              tp_axis: Optional[str] = None) -> jax.Array:
    """One transformer block. In manual mode the weights arriving here are
    the local TP shard (wide axis pre-sliced) and attention/MoE take the
    collective axes to use; in GSPMD mode all axes are None."""
    dt = cfg.dtype
    B, S, _D = x.shape

    # ---- attention ----------------------------------------------------------
    h = rms_norm(x, lp["attn_norm"])
    q, k, v = _project_qkv(cfg, lp, h, positions)
    if sp_axis is not None:
        Hq, Hkv = q.shape[2], k.shape[2]
        if Hq != Hkv:
            k = jnp.repeat(k, Hq // Hkv, axis=2)
            v = jnp.repeat(v, Hq // Hkv, axis=2)
        o = ring_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), axis_name=sp_axis, causal=True,
        ).transpose(0, 2, 1, 3)
    else:
        o = _attention_dense(q, k, v)
    o = o.reshape(B, S, -1) @ lp["wo"].astype(dt)
    if tp_axis is not None:
        o = lax.psum(o, tp_axis)  # row-parallel output proj
    x = x + o

    # ---- mlp ---------------------------------------------------------------
    h = rms_norm(x, lp["mlp_norm"])
    return x + _mlp_block(cfg, lp, h, layer_idx,
                          tp_axis=tp_axis, ep_axis=ep_axis)


def _mlp_block(cfg: TransformerConfig, lp: Dict[str, jax.Array],
               h: jax.Array, layer_idx: jax.Array,
               tp_axis: Optional[str] = None,
               ep_axis: Optional[str] = None) -> jax.Array:
    """Post-norm MLP/MoE for one layer over ``h`` [B, S, D] — shared
    between the training layer body and the decode path (where S == 1)."""
    dt = cfg.dtype
    B, S, D = h.shape
    if cfg.num_experts and "router" in lp:
        is_moe = (layer_idx % cfg.moe_every) == (cfg.moe_every - 1)
        logits = (h.astype(jnp.float32)
                  @ lp["router"].astype(jnp.float32)).reshape(
            B * S, cfg.num_experts)

        def expert_fn(tok):  # [E_local, C, D]
            g = jnp.einsum("ecd,edf->ecf", tok, lp["e_gate"].astype(dt))
            u = jnp.einsum("ecd,edf->ecf", tok, lp["e_up"].astype(dt))
            out = jnp.einsum(
                "ecf,efd->ecd", jax.nn.silu(g) * u, lp["e_down"].astype(dt))
            if tp_axis is not None:
                out = lax.psum(out, tp_axis)  # row-parallel e_down
            return out

        if ep_axis is not None:
            moe_out = moe_dispatch_combine(
                h.reshape(B * S, D), logits, expert_fn,
                num_experts=cfg.num_experts,
                capacity_factor=cfg.capacity_factor,
                axis_name=ep_axis).reshape(B, S, D)
        else:
            # Dense fallback: run all experts, weight by top-1 gate.
            probs = jax.nn.softmax(logits, axis=-1)
            top = jnp.argmax(probs, axis=-1)
            gate = probs[jnp.arange(B * S), top].astype(dt)
            toks = jnp.broadcast_to(
                h.reshape(1, B * S, D), (cfg.num_experts, B * S, D))
            outs = expert_fn(toks)
            moe_out = (outs[top, jnp.arange(B * S)]
                       * gate[:, None]).reshape(B, S, D)
        if cfg.moe_every == 1:
            return moe_out  # all layers MoE: skip the dense branch
        dense_out = _swiglu(cfg, lp, h, tp_axis)
        return jnp.where(is_moe, moe_out, dense_out)
    return _swiglu(cfg, lp, h, tp_axis)


def _swiglu(cfg, lp, h, tp_axis):
    dt = cfg.dtype
    g = h @ lp["w_gate"].astype(dt)
    u = h @ lp["w_up"].astype(dt)
    out = (jax.nn.silu(g) * u) @ lp["w_down"].astype(dt)
    if tp_axis is not None:
        out = lax.psum(out, tp_axis)  # row-parallel down proj
    return out


def forward(cfg: TransformerConfig, params: Dict[str, Any],
            tokens: jax.Array,
            mesh: Optional[Mesh] = None,
            rules: Optional[ShardingRules] = None) -> jax.Array:
    """GSPMD path: tokens [B, S] -> logits [B, S, V]. Layers via lax.scan."""
    r = rules or ShardingRules()

    def constrain(x, *logical):
        if mesh is None:
            return x
        return lax.with_sharding_constraint(
            x, NamedSharding(mesh, r.spec(*logical)))

    B, S = tokens.shape
    dt = cfg.dtype
    x = params["embed"].astype(dt)[tokens]
    x = constrain(x, "batch", "sequence", "embed")
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))

    def body(carry, lp_with_idx):
        x = carry
        lp, idx = lp_with_idx

        def run(x):
            return _layer_fn(cfg, lp, x, positions, idx)

        x = jax.checkpoint(run)(x) if cfg.remat else run(x)
        x = constrain(x, "batch", "sequence", "embed")
        return x, None

    idxs = jnp.arange(cfg.n_layers)
    x, _ = lax.scan(body, x, (params["layers"], idxs))
    x = rms_norm(x, params["final_norm"])
    logits = x @ params["lm_head"].astype(dt)
    return constrain(logits.astype(jnp.float32), "batch", "sequence", "vocab")


def loss_fn(cfg: TransformerConfig, params, tokens, targets,
            mesh=None, rules=None) -> jax.Array:
    logits = forward(cfg, params, tokens, mesh, rules)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


# ---------------------------------------------------------------------------
# Manual SPMD training step: shard_map over (dp, pp, tp, sp, ep).
# ---------------------------------------------------------------------------

def _stage_params_spec(cfg: TransformerConfig) -> Dict[str, P]:
    """in_specs for the stacked layer tree inside shard_map: leading layer
    axis sharded over pp, wide weight axes over tp, experts over ep."""
    sp = {
        "attn_norm": P("pp", None),
        "wq": P("pp", None, "tp"), "wk": P("pp", None, "tp"),
        "wv": P("pp", None, "tp"), "wo": P("pp", "tp", None),
        "mlp_norm": P("pp", None),
        "w_gate": P("pp", None, "tp"), "w_up": P("pp", None, "tp"),
        "w_down": P("pp", "tp", None),
    }
    if cfg.num_experts:
        sp.update({
            "router": P("pp", None, None),
            "e_gate": P("pp", "ep", None, "tp"),
            "e_up": P("pp", "ep", None, "tp"),
            "e_down": P("pp", "ep", "tp", None),
        })
    return sp


def make_spmd_train_step(cfg: TransformerConfig, mesh: Mesh, params,
                         optimizer=None, n_microbatches: int = 2):
    """Build the manual multi-chip training step.

    Returns ``(step, pspec, ospec)`` where ``step(params, opt_state,
    tokens, targets) -> (params, opt_state, loss)`` is a jitted
    ``shard_map`` over the full mesh with explicit collectives on every
    axis, and pspec/ospec are the PartitionSpec trees for params and
    optimizer state (``params`` is only shape-inspected — pass real or
    ``jax.eval_shape`` abstract values).

    Requires cfg.n_layers % pp == 0, heads % tp == 0, batch % (dp*mb) == 0,
    seq % sp == 0, experts % ep == 0 (when MoE).
    """
    import optax

    if optimizer is None:
        optimizer = optax.adamw(3e-4)
    shape = mesh_shape(mesh)
    pp, tp, sp_n, ep_n = shape["pp"], shape["tp"], shape["sp"], shape["ep"]
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers {cfg.n_layers} % pp {pp} != 0")
    if cfg.n_heads % tp or cfg.n_kv_heads % tp:
        raise ValueError("heads must divide tp")
    if cfg.num_experts and cfg.num_experts % ep_n:
        raise ValueError("experts must divide ep")
    layers_per_stage = cfg.n_layers // pp

    lp_spec = _stage_params_spec(cfg)
    pspec = {
        "embed": P(None, None),
        "layers": lp_spec,
        "final_norm": P(None),
        "lm_head": P(None, None),
    }
    data_spec = P(("dp", "fsdp"), "sp")

    sp_axis = "sp" if sp_n > 1 else None
    ep_axis = "ep" if ep_n > 1 else None
    tp_axis = "tp" if tp > 1 else None

    def stage_fn(stage_layers, act, stage_idx):
        """Run this pp-shard's layers_per_stage layers over activation
        bucket act = (x, positions)."""
        x, positions = act

        def body(carry, lp_i):
            lp, local_i = lp_i
            gidx = stage_idx * layers_per_stage + local_i

            def run(x):
                return _layer_fn(cfg, lp, x, positions, gidx,
                                 sp_axis=sp_axis, ep_axis=ep_axis,
                                 tp_axis=tp_axis)

            x = jax.checkpoint(run)(carry) if cfg.remat else run(carry)
            return x, None

        x, _ = lax.scan(
            body, x, (stage_layers, jnp.arange(layers_per_stage)))
        return x, positions

    def local_loss(params, tokens, targets):
        """Per-shard loss: tokens [B_local, S_local] (dp×sp sharded)."""
        B, S = tokens.shape
        dt = cfg.dtype
        stage = lax.axis_index("pp")
        x = params["embed"].astype(dt)[tokens]
        s_idx = lax.axis_index("sp") if sp_n > 1 else 0
        positions = jnp.broadcast_to(
            jnp.arange(S) + s_idx * S, (B, S))

        if pp > 1:
            from ray_tpu.parallel.pipeline import pipeline_spmd
            mb = n_microbatches
            if B % mb:
                raise ValueError(f"local batch {B} % microbatches {mb}")
            xs = x.reshape(mb, B // mb, S, -1)
            pos_mb = jnp.broadcast_to(positions[: B // mb], xs.shape[:3])
            out, _ = pipeline_spmd(
                lambda lp, act: stage_fn(lp, act, lax.axis_index("pp")),
                params["layers"], (xs, pos_mb), axis_name="pp")
            x = out.reshape(B, S, -1)
        else:
            x, _ = stage_fn(params["layers"], (x, positions),
                            jnp.zeros((), jnp.int32))

        x = rms_norm(x, params["final_norm"])
        logits = (x @ params["lm_head"].astype(dt)).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    from ray_tpu.parallel.mesh import AXES

    n_total = math.prod(shape[a] for a in AXES)

    def _sync_grads(grads):
        """Per-leaf gradient sync. Inside shard_map, jax.grad returns on
        each shard d(sum of every shard's local_loss)/d(local leaf). Since
        local_loss is the local-token mean (distinct across dp/fsdp/sp,
        replicated as a function across tp/pp/ep), the global-mean gradient
        of a leaf sharded over axes S is psum over the complement of S,
        scaled by 1/N_devices — one rule covers replicated and sharded
        leaves alike."""
        flat_g, treedef = jax.tree.flatten(grads)
        flat_s = jax.tree.leaves(pspec, is_leaf=lambda x: isinstance(x, P))
        out = []
        for g, s in zip(flat_g, flat_s):
            sharded = set()
            for part in s:
                if part is None:
                    continue
                for ax in (part if isinstance(part, tuple) else (part,)):
                    sharded.add(ax)
            repl = tuple(a for a in AXES if a not in sharded)
            out.append(lax.psum(g, repl) / n_total)
        return jax.tree.unflatten(treedef, out)

    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(local_loss)(params, tokens, targets)
        grads = _sync_grads(grads)
        loss = lax.pmean(loss, ("dp", "fsdp", "sp"))
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    # Optimizer-state sharding: optax states embed whole param-shaped
    # subtrees (mu/nu — must carry the params' specs) plus scalar leaves
    # (counts — replicate). Substitute pspec wherever a subtree's treedef
    # matches the params' treedef; shape-matching would be unsound (wq/wo
    # share a global shape but transpose their tp axis).
    params_treedef = jax.tree.structure(params)

    def _is_param_tree(x):
        try:
            return jax.tree.structure(x) == params_treedef
        except Exception:
            return False

    opt_shapes = jax.eval_shape(optimizer.init, params)
    ospec = jax.tree.map(
        lambda sub: pspec if _is_param_tree(sub) else P(),
        opt_shapes, is_leaf=_is_param_tree)

    step_sm = jax.shard_map(
        step, mesh=mesh,
        in_specs=(pspec, ospec, data_spec, data_spec),
        out_specs=(pspec, ospec, P()),
        check_vma=False)
    return jax.jit(step_sm), pspec, ospec


def shard_params_for_step(params, mesh, pspec):
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, pspec)


# ---------------------------------------------------------------------------
# Inference path: paged KV cache + prefill / chunked prefill / decode.
#
# The training path above is cacheless (recomputes all K/V every call);
# serving needs the Orca/vLLM shape — K/V of every processed token persists
# in fixed-size blocks of preallocated HBM arrays, indexed per sequence
# through a block table, so the continuous-batching engine
# (ray_tpu/llm/) admits/evicts sequences by moving integers, never bytes.
# GQA indexes the cache at n_kv_heads width throughout (grouped queries —
# see ops/paged_attention.py); the n_heads-wide repeat never exists here.
#
# Tensor parallelism: every function below takes optional ``mesh``/
# ``rules``. With a mesh, the Megatron recipe from ``parallel/`` is
# grafted onto the cached path — wq/wk/wv column-sharded on tp (per-chip
# head shards), wo/w_down row-sharded (GSPMD inserts the psum), and the
# KV pool sharded along n_kv_heads (parallel.sharding.kv_cache_specs),
# so model + cache scale past one chip while block bookkeeping stays
# global integers. Constraints keep activations on the tp axis between
# the projections; without a mesh every constraint is a no-op.
# ---------------------------------------------------------------------------


def _infer_constrain(x, mesh, rules, *logical):
    """Sharding annotation for the inference path (no-op without mesh)."""
    from ray_tpu.parallel.sharding import constrain_logical

    return constrain_logical(x, mesh, rules, *logical)

def init_kv_cache(cfg: TransformerConfig, num_blocks: int, block_size: int,
                  dtype: Any = None) -> Dict[str, jax.Array]:
    """Preallocate the paged KV pool: ``[L, num_blocks, block_size,
    n_kv_heads, head_dim]`` for K and V. Block 0 is conventionally the
    NULL block (padding writes land there — see ray_tpu/llm/kv_cache.py);
    zeros-initialized so unwritten slots are finite and mask-safe."""
    dt = dtype or cfg.dtype
    shape = (cfg.n_layers, num_blocks, block_size,
             cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def prefill_with_cache(cfg: TransformerConfig, params, cache,
                       tokens: jax.Array, prompt_lens: jax.Array,
                       block_tables: jax.Array
                       ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Process right-padded prompts, writing every position's K/V into
    the paged cache, and return the last-real-position logits.

    tokens [B, S] int32 (padded rows/tails may be anything);
    prompt_lens [B]; block_tables [B, M] with M*block_size >= S (padded
    entries point at the null block, so out-of-prompt writes are trash
    writes into block 0 — never another sequence's block).

    Returns (logits [B, vocab] f32 at position prompt_lens-1, new cache).
    Causality makes the padded tail invisible to every real position, so
    the result is bit-identical to an unpadded per-sequence run.
    """
    B, S = tokens.shape
    dt = cfg.dtype
    block_size = cache["k"].shape[2]
    x = params["embed"].astype(dt)[tokens]
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    # Physical slot of every position: (block_tables[b, s//bs], s % bs).
    blk = jnp.take_along_axis(block_tables, positions // block_size,
                              axis=1)                       # [B, S]
    off = positions % block_size

    def body(carry, lp_idx):
        x, ck, cv = carry
        lp, idx = lp_idx
        h = rms_norm(x, lp["attn_norm"])
        q, k, v = _project_qkv(cfg, lp, h, positions)
        ck = ck.at[idx, blk, off].set(k)
        cv = cv.at[idx, blk, off].set(v)
        o = _attention_dense(q, k, v, causal=True, grad=False)
        x = x + o.reshape(B, S, -1) @ lp["wo"].astype(dt)
        h = rms_norm(x, lp["mlp_norm"])
        x = x + _mlp_block(cfg, lp, h, idx)
        return (x, ck, cv), None

    idxs = jnp.arange(cfg.n_layers)
    (x, ck, cv), _ = lax.scan(
        body, (x, cache["k"], cache["v"]), (params["layers"], idxs))
    x = rms_norm(x, params["final_norm"])
    last = jnp.take_along_axis(
        x, (prompt_lens - 1)[:, None, None].clip(0), axis=1)[:, 0]
    logits = (last @ params["lm_head"].astype(dt)).astype(jnp.float32)
    return logits, {"k": ck, "v": cv}


def prefill_chunk(cfg: TransformerConfig, params, cache,
                  tokens: jax.Array, start_pos: jax.Array,
                  chunk_lens: jax.Array, block_tables: jax.Array,
                  mesh=None, rules=None
                  ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Process one CHUNK of each prompt against the paged cache: tokens
    ``[B, C]`` are each sequence's prompt slice starting at absolute
    position ``start_pos[b]``, attending over everything already in the
    cache (prefix-cache hits, earlier chunks) plus the chunk itself.

    This one program is both halves of the prefill fast path:

    - **chunked prefill** — a long prompt runs as several calls with
      advancing ``start_pos``, so the decode batch's inter-token stall
      is bounded by one chunk, not one prompt;
    - **prefix-cache skip** — a prompt whose leading blocks were shared
      by ``PagedKVCache.allocate_prefix`` starts its FIRST chunk at the
      cached length and never recomputes the shared tokens.

    tokens [B, C] int32 (rows/tails may be anything past chunk_lens);
    start_pos [B]; chunk_lens [B] (valid tokens in this chunk);
    block_tables [B, M] covering position start_pos + C - 1 (padded
    entries point at the null block — out-of-range writes are trash
    writes into block 0, masked out of every softmax).

    Returns (logits [B, vocab] f32 at the chunk's LAST valid position —
    meaningful only for rows whose chunk completes the prompt — and the
    new cache).
    """
    x, ck, cv = _chunk_scan(cfg, params, cache, tokens, start_pos,
                            block_tables, mesh, rules)
    last = jnp.take_along_axis(
        x, (chunk_lens - 1)[:, None, None].clip(0), axis=1)[:, 0]
    logits = (last @ params["lm_head"].astype(cfg.dtype)
              ).astype(jnp.float32)
    return logits, {"k": ck, "v": cv}


def _chunk_scan(cfg: TransformerConfig, params, cache, tokens, start_pos,
                block_tables, mesh, rules):
    """Shared multi-token body of ``prefill_chunk`` and ``verify_step``:
    run the chunk through every layer against the paged cache, writing
    each position's K/V before it is attended, and return the final-
    normed hidden states ``[B, C, D]`` plus the updated K/V pools."""
    B, C = tokens.shape
    dt = cfg.dtype
    block_size = cache["k"].shape[2]
    M = block_tables.shape[1]
    x = params["embed"].astype(dt)[tokens]
    positions = start_pos[:, None] + jnp.arange(C)[None, :]    # [B, C]
    blk = jnp.take_along_axis(
        block_tables, jnp.minimum(positions // block_size, M - 1),
        axis=1)                                                # [B, C]
    off = positions % block_size

    from ray_tpu.ops.paged_attention import paged_attention_prefill

    def body(carry, lp_idx):
        x, ck, cv = carry
        lp, idx = lp_idx
        h = rms_norm(x, lp["attn_norm"])
        q, k, v = _project_qkv(cfg, lp, h, positions)
        q = _infer_constrain(q, mesh, rules, None, None, "heads",
                             "head_dim")
        k = _infer_constrain(k, mesh, rules, None, None, "kv_heads",
                             "head_dim")
        v = _infer_constrain(v, mesh, rules, None, None, "kv_heads",
                             "head_dim")
        # Write the chunk's K/V, then attend over [0, position] per
        # token — each new slot is part of its own context.
        ck = ck.at[idx, blk, off].set(k)
        cv = cv.at[idx, blk, off].set(v)
        o = paged_attention_prefill(q, ck[idx], cv[idx], block_tables,
                                    positions, mesh=mesh, rules=rules)
        x = x + o.reshape(B, C, -1) @ lp["wo"].astype(dt)
        h = rms_norm(x, lp["mlp_norm"])
        x = x + _mlp_block(cfg, lp, h, idx)
        return (x, ck, cv), None

    idxs = jnp.arange(cfg.n_layers)
    (x, ck, cv), _ = lax.scan(
        body, (x, cache["k"], cache["v"]), (params["layers"], idxs))
    return rms_norm(x, params["final_norm"]), ck, cv


def verify_step(cfg: TransformerConfig, params, cache,
                tokens: jax.Array, start_pos: jax.Array,
                block_tables: jax.Array, mesh=None, rules=None
                ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Speculative-decode VERIFY: advance each sequence by ``C`` tokens
    in ONE program and return the logits at EVERY position — the
    chunked-prefill multi-token path generalized from last-position
    logits to all-position logits, so the flagship can score a draft
    model's k proposals (positions carry token i's context -> logits
    for token i+1) in a single batched step instead of k decode steps.

    tokens [B, C] int32 — row b holds the verified context's last
    accepted token followed by the draft's proposals, starting at
    absolute position ``start_pos[b]``; block_tables as in
    ``prefill_chunk`` (padded rows aim at the NULL block).

    Returns (logits [B, C, vocab] f32, new cache). K/V for ALL C
    positions is written — including positions whose draft token is
    later REJECTED. That is safe by the same invariant chunked prefill
    relies on: each layer writes a position's K/V before any later
    position attends, and the engine always overwrites a rejected
    position's slot (with the corrected token's K/V) before any
    subsequent step attends over it.
    """
    x, ck, cv = _chunk_scan(cfg, params, cache, tokens, start_pos,
                            block_tables, mesh, rules)
    logits = (x @ params["lm_head"].astype(cfg.dtype)
              ).astype(jnp.float32)
    return logits, {"k": ck, "v": cv}


def decode_step(cfg: TransformerConfig, params, cache,
                tokens: jax.Array, positions: jax.Array,
                block_tables: jax.Array, mesh=None, rules=None
                ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One continuous-batching iteration: each sequence advances by one
    token against its paged context.

    tokens [B] int32 (the token AT ``positions``, usually last sampled);
    positions [B] int32 (0-based; context length becomes positions+1);
    block_tables [B, M] int32. Padded batch rows should carry position 0
    and a null block table — their writes land in block 0 and their
    logits are garbage the caller ignores.

    Returns (logits [B, vocab] f32, new cache).
    """
    B = tokens.shape[0]
    dt = cfg.dtype
    block_size = cache["k"].shape[2]
    x = params["embed"].astype(dt)[tokens][:, None]  # [B, 1, D]
    pos2 = positions[:, None]                        # [B, 1]
    context_lens = positions + 1
    blk = jnp.take_along_axis(block_tables, pos2 // block_size,
                              axis=1)[:, 0]          # [B]
    off = positions % block_size

    from ray_tpu.ops.paged_attention import paged_attention_decode

    def body(carry, lp_idx):
        x, ck, cv = carry
        lp, idx = lp_idx
        h = rms_norm(x, lp["attn_norm"])
        q, k, v = _project_qkv(cfg, lp, h, pos2)
        q = _infer_constrain(q, mesh, rules, None, None, "heads",
                             "head_dim")
        k = _infer_constrain(k, mesh, rules, None, None, "kv_heads",
                             "head_dim")
        v = _infer_constrain(v, mesh, rules, None, None, "kv_heads",
                             "head_dim")
        # Write THIS token's k/v, then attend over [0, positions] —
        # the new slot is part of its own context (self-attention).
        ck = ck.at[idx, blk, off].set(k[:, 0])
        cv = cv.at[idx, blk, off].set(v[:, 0])
        o = paged_attention_decode(
            q[:, 0], ck[idx], cv[idx], block_tables, context_lens,
            mesh=mesh, rules=rules)
        x = x + (o.reshape(B, 1, -1) @ lp["wo"].astype(dt))
        h = rms_norm(x, lp["mlp_norm"])
        x = x + _mlp_block(cfg, lp, h, idx)
        return (x, ck, cv), None

    idxs = jnp.arange(cfg.n_layers)
    (x, ck, cv), _ = lax.scan(
        body, (x, cache["k"], cache["v"]), (params["layers"], idxs))
    x = rms_norm(x[:, 0], params["final_norm"])
    logits = (x @ params["lm_head"].astype(dt)).astype(jnp.float32)
    return logits, {"k": ck, "v": cv}
