"""Continuous-batching inference engine over the flagship Transformer
(reference role: vLLM's LLMEngine / Ray Serve LLM's engine actor).

One ``InferenceEngine`` owns a paged KV cache pool (with copy-on-write
shared prefix blocks), a continuous-batching scheduler (with chunked
prefill), and two jitted programs over ``models.transformer``:

- ``prefill_chunk``: prompt slices, padded to a (batch, chunk) bucket,
  write their K/V into their allocated blocks in one program; a slice
  that completes its prompt produces the request's FIRST generated
  token. A prompt whose leading blocks hit the prefix cache starts its
  first chunk at the cached length — the shared tokens are never
  recomputed (``prefill_tokens_saved``). A prompt longer than the
  prefill token budget runs as several chunks across iterations, so
  the running batch's inter-token stall is bounded by one chunk.
- ``decode_step``: every fully-prefilled sequence advances one token
  per iteration in one program — Orca's iteration-level batching, so a
  new request joins the batch at the next step boundary instead of
  waiting for the batch to drain, and a finished sequence leaves it
  (and drops its block refs) immediately.

Tensor parallelism (``EngineConfig.tp_size``): the Megatron recipe from
``parallel/`` grafts onto both programs — per-layer weights column/row
sharded on the tp mesh axis, the KV pool sharded along ``n_kv_heads``
(each chip holds its head shard's blocks; block IDS stay global), GSPMD
inserting the psums — so model + cache scale past one chip while the
host-side scheduler and block manager are unchanged. TP decode is
asserted token-for-token identical to single-device decode.

Padding buckets are powers of two, so the number of distinct compiled
programs is logarithmic in the caps. Padded rows aim at the NULL block
and their logits are ignored; because attention masks every slot past a
sequence's context length, a sequence's tokens are IDENTICAL whatever
batch it happened to share an iteration with — the engine's
concurrent-equals-sequential parity test pins exactly that.

Requests stream: ``generate()`` yields token ids as iterations commit
them (time-to-first-token ≈ one prefill — one TAIL chunk when the
prefix cache hits), and closing the consumer (``GeneratorExit``)
cancels the sequence — its private blocks return to the pool
immediately (shared prefix blocks stay with their other holders),
unblocking parked admissions. The engine is thread-safe; a Serve
replica drives it from concurrent streaming handlers with no extra
locking.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
import weakref
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ray_tpu.llm.kv_cache import KVCacheOOM, PagedKVCache  # noqa: F401
from ray_tpu.exceptions import RequestSheddedError
from ray_tpu.llm.scheduler import (
    CANCELLED,
    FAILED,
    FINISHED,
    SHED,
    EngineQueueFull,
    Request,
    Scheduler,
)

__all__ = ["EngineConfig", "InferenceEngine", "live_engines"]

_DONE = "__done__"
_ERROR = "__error__"

# Live engines in this process, for util/state + the dashboard (weak:
# observability must never keep a dead engine's KV pool alive).
_ENGINES: "weakref.WeakValueDictionary[int, InferenceEngine]" = \
    weakref.WeakValueDictionary()
_engine_ids = iter(range(1, 1 << 62))


def live_engines() -> List["InferenceEngine"]:
    """Engines constructed in this process and not yet GC'd (shutdown
    engines remain listed until collected — their final counters are
    still readable)."""
    return [e for _, e in sorted(_ENGINES.items())]


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Engine knobs. ``model`` is the flagship TransformerConfig; the
    KV pool holds ``num_blocks`` blocks of ``block_size`` tokens each
    (block 0 reserved), shared by every live sequence."""

    model: Any = None                  # models.TransformerConfig
    num_blocks: int = 128
    block_size: int = 16
    max_num_seqs: int = 8              # iteration batch cap
    prefill_token_budget: int = 2048   # prompt tokens computed per step
    max_queued_requests: int = 64      # bounded waitqueue (admission)
    eos_token_id: Optional[int] = None
    max_new_tokens_default: int = 64
    param_seed: int = 0
    cache_dtype: Any = None            # default: model dtype
    enable_prefix_caching: bool = True  # COW shared prefix blocks
    tp_size: int = 1                   # tensor-parallel mesh width

    def resolved_model(self):
        if self.model is not None:
            return self.model
        from ray_tpu.models import TransformerConfig

        return TransformerConfig()


def _pow2_at_least(n: int, floor: int = 1) -> int:
    m = max(int(n), floor)
    p = 1
    while p < m:
        p *= 2
    return p


class InferenceEngine:
    """See module docstring. Construct with real ``params`` or let the
    engine init them from ``param_seed`` (every Serve replica of one
    deployment then serves identical weights with zero shipping)."""

    def __init__(self, config: Optional[EngineConfig] = None,
                 params: Optional[dict] = None):
        import jax
        from functools import partial

        from ray_tpu.models import (
            decode_step,
            init_params,
            prefill_chunk,
        )

        self.config = config or EngineConfig()
        self.model_cfg = self.config.resolved_model()
        if params is None:
            params = init_params(
                self.model_cfg, jax.random.PRNGKey(self.config.param_seed))
        self.mesh = None
        rules = None
        if self.config.tp_size > 1:
            self.mesh, rules = self._build_tp_mesh(self.config.tp_size)
            params = self._shard_params(params, rules)
        self.params = params
        self.cache = PagedKVCache(
            self.model_cfg, self.config.num_blocks, self.config.block_size,
            dtype=self.config.cache_dtype,
            enable_prefix_caching=self.config.enable_prefix_caching,
            mesh=self.mesh, rules=rules)
        self.scheduler = Scheduler(
            self.cache,
            max_num_seqs=self.config.max_num_seqs,
            prefill_token_budget=self.config.prefill_token_budget,
            max_queued_requests=self.config.max_queued_requests)
        # Donation rewrites the cache in place on accelerators; the CPU
        # backend only warns, so skip it there to keep logs clean.
        backend = jax.default_backend()
        donate = (1,) if backend != "cpu" else ()
        self._prefill_chunk = jax.jit(
            partial(prefill_chunk, self.model_cfg, mesh=self.mesh,
                    rules=rules),
            donate_argnums=donate)
        self._decode = jax.jit(
            partial(decode_step, self.model_cfg, mesh=self.mesh,
                    rules=rules),
            donate_argnums=donate)
        self._lock = threading.RLock()          # scheduler + cache + step
        self._work = threading.Event()          # submit -> loop wakeup
        self._stop = threading.Event()
        self._loop_thread: Optional[threading.Thread] = None
        self._requests: Dict[int, Request] = {}
        # -- counters --
        self.num_steps = 0
        self.num_prefill_tokens = 0      # prompt tokens actually computed
        self.num_generated_tokens = 0
        # Per-request TTFT decomposition records (queue/prefill/decode/
        # ttft seconds), bounded: stats() serves percentile rollups —
        # the elastic episode's "where does TTFT live" evidence.
        from collections import deque as _deque

        self._timings: "_deque" = _deque(maxlen=2048)
        self.engine_id = next(_engine_ids)
        _ENGINES[self.engine_id] = self
        # Flight-recorder section: this engine's waitqueue depth, KV
        # occupancy, and TTFT decomposition render into every debug
        # bundle (weak-registered — a GC'd engine stops reporting via
        # the WeakValueDictionary, and stats() raising on a dead engine
        # is caught per-section at dump time).
        from ray_tpu._private import flight as _flight

        if _flight.active():
            eid = self.engine_id

            def _section(_id=eid):
                e = _ENGINES.get(_id)
                return e.stats() if e is not None else {"gone": True}

            _flight.add_section(f"llm.engine-{eid}", _section)

    # ------------------------------------------------------ tensor parallel
    @staticmethod
    def _build_tp_mesh(tp: int):
        """A tp-only mesh over the first ``tp`` devices (the standard
        framework axes, every other axis size 1, so the default
        ShardingRules apply unchanged — batch axes become no-op
        shards)."""
        import os

        import jax

        from ray_tpu.parallel.mesh import MeshConfig, make_mesh
        from ray_tpu.parallel.sharding import ShardingRules

        platform = os.environ.get("RAY_TPU_PLATFORM")
        devices = jax.devices(platform) if platform else jax.devices()
        if len(devices) < tp:
            raise ValueError(
                f"tp_size {tp} exceeds {len(devices)} visible devices")
        mesh = make_mesh(MeshConfig(dp=1, fsdp=1, pp=1, tp=tp, sp=1, ep=1),
                         devices=devices[:tp])
        return mesh, ShardingRules()

    def _shard_params(self, params, rules):
        cfg = self.model_cfg
        if cfg.n_heads % self.config.tp_size or \
                cfg.n_kv_heads % self.config.tp_size:
            raise ValueError(
                f"n_heads {cfg.n_heads} / n_kv_heads {cfg.n_kv_heads} "
                f"must divide tp_size {self.config.tp_size}")
        from ray_tpu.models import param_specs
        from ray_tpu.parallel.sharding import shard_params

        return shard_params(params, self.mesh, param_specs(cfg, rules))

    # ------------------------------------------------------------ lifecycle
    def _ensure_loop(self):
        if self._loop_thread is None or not self._loop_thread.is_alive():
            self._loop_thread = threading.Thread(
                target=self._loop, daemon=True, name="llm-engine-step")
            self._loop_thread.start()

    def shutdown(self):
        self._stop.set()
        with self._lock:
            for req in list(self._requests.values()):
                if not req.finished():
                    # Remove from the waitqueue BEFORE finishing: a loop
                    # thread already past its stop-check blocks on this
                    # lock and would otherwise re-admit the CANCELLED
                    # request (reallocating blocks, streaming past DONE).
                    self.scheduler.remove_waiting(req)
                    self._finish(req, CANCELLED)
        self._work.set()

    def _loop(self):
        while not self._stop.is_set():
            self._work.wait()
            if self._stop.is_set():
                return
            try:
                busy = self.step()
            except Exception as exc:  # noqa: BLE001 — engine must not die
                # An unexpected step failure (compile error, device OOM)
                # must not strand consumers on a dead loop thread: fail
                # every in-flight request TYPED (freeing its blocks) and
                # keep serving — the next submit sees a clean engine.
                with self._lock:
                    for req in list(self._requests.values()):
                        if not req.finished():
                            self.scheduler.remove_waiting(req)
                            self._finish(req, FAILED, exc)
                busy = True
                continue
            if not busy:
                idle = False
                with self._lock:
                    # Check + clear under the submit lock: a concurrent
                    # submit either lands before the check (not idle) or
                    # blocks until after the clear and re-sets the event.
                    if (not self.scheduler.running
                            and self.scheduler.queue_depth() == 0):
                        self._work.clear()
                        idle = True
                if not idle:
                    # Defensive: a non-admittable queue must not busy-spin.
                    time.sleep(0.001)

    # -------------------------------------------------------------- request
    def submit(self, prompt: List[int],
               max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               temperature: float = 0.0,
               seed: Optional[int] = None,
               priority: int = 0,
               trace=None) -> Request:
        """Enqueue a request. Past the bounded waitqueue the LOWEST
        priority class loses: either this submit raises
        ``EngineQueueFull`` (a ``RequestSheddedError``) or a worse
        already-waiting request is evicted with a typed
        ``RequestSheddedError`` on its stream — overload degrades by
        policy, not by timeout. Tokens arrive on ``req.output_queue``
        as iterations commit them."""
        req = Request(
            prompt,
            max_new_tokens if max_new_tokens is not None
            else self.config.max_new_tokens_default,
            eos_token_id=(eos_token_id if eos_token_id is not None
                          else self.config.eos_token_id),
            temperature=temperature, seed=seed, priority=priority)
        req.trace = trace
        # Reject what can NEVER be served: a completion longer than the
        # model's context window, or one larger than the whole pool.
        # (Prompts over the prefill token budget are FINE — chunked
        # prefill spreads them across iterations.)
        total = len(req.prompt) + req.max_new_tokens
        max_len = getattr(self.model_cfg, "max_seq_len", None)
        if max_len is not None and total > max_len:
            raise ValueError(
                f"prompt + max_new_tokens = {total} exceeds the model's "
                f"max_seq_len {max_len}")
        if self.cache.blocks_for_tokens(total) > self.cache.usable_blocks:
            raise KVCacheOOM(
                f"request needs {self.cache.blocks_for_tokens(total)} "
                f"blocks for {total} tokens; pool holds "
                f"{self.cache.usable_blocks}")
        with self._lock:
            victim = self.scheduler.submit(req)
            if victim is not None:
                # Evicted pre-admission (never held blocks): its consumer
                # gets the typed shed error, counted apart from failures.
                self._finish(victim, SHED, RequestSheddedError(
                    f"request (priority class {victim.priority}) evicted "
                    f"from the waitqueue by a class-{req.priority} "
                    f"arrival under overload",
                    priority=victim.priority))
            self._requests[req.seq_id] = req
            self._work.set()
        self._ensure_loop()
        return req

    def generate(self, prompt: List[int],
                 max_new_tokens: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0,
                 seed: Optional[int] = None,
                 priority: int = 0,
                 timeout_s: float = 120.0,
                 trace=None) -> Iterator[int]:
        """Streaming generator of token ids. Closing it mid-generation
        (``close()`` / GC / a Serve stream cancel) frees the sequence's
        private KV blocks immediately."""
        req = self.submit(prompt, max_new_tokens=max_new_tokens,
                          eos_token_id=eos_token_id,
                          temperature=temperature, seed=seed,
                          priority=priority, trace=trace)
        try:
            while True:
                try:
                    item = req.output_queue.get(timeout=timeout_s)
                except queue.Empty:
                    raise TimeoutError(
                        f"no token for {timeout_s}s (sequence "
                        f"{req.seq_id}, status {req.status})") from None
                if isinstance(item, tuple):
                    kind, payload = item
                    if kind == _DONE:
                        return
                    raise payload  # _ERROR
                yield item
        finally:
            if not req.finished():
                self.cancel(req)

    def cancel(self, req) -> bool:
        """Cancel by Request or seq_id: removes it from the waitqueue or
        the running set and drops its block refs NOW."""
        with self._lock:
            if isinstance(req, int):
                req = self._requests.get(req)
            if req is None or req.finished():
                return False
            self.scheduler.remove_waiting(req)
            self._finish(req, CANCELLED)
        self._work.set()  # a parked admission may now fit
        return True

    def _finish(self, req: Request, status: str,
                error: Optional[BaseException] = None):
        self.scheduler.release(req, status, error)
        self._requests.pop(req.seq_id, None)
        req.t_finish = time.monotonic()
        self._record_timing(req, status)
        if status in (FAILED, SHED) and error is not None:
            req.output_queue.put((_ERROR, error))
        else:
            req.output_queue.put((_DONE, status))

    def _record_timing(self, req: Request, status: str):
        """TTFT decomposition record + (when the request carried a trace
        context) llm.queue / llm.prefill / llm.decode spans with a
        first_token event — the per-request waterfall's engine rows."""
        t_end = req.t_finish
        queue_s = ((req.t_sched - req.t_submit)
                   if req.t_sched is not None else t_end - req.t_submit)
        prefill_s = ((req.t_prefill_done - req.t_sched)
                     if req.t_sched is not None
                     and req.t_prefill_done is not None else 0.0)
        decode_s = ((t_end - req.t_prefill_done)
                    if req.t_prefill_done is not None else 0.0)
        self._timings.append({
            "status": status,
            "queue_s": queue_s,
            "prefill_s": prefill_s,
            "decode_s": decode_s,
            "ttft_s": ((req.t_first_token - req.t_submit)
                       if req.t_first_token is not None else None),
            "total_s": t_end - req.t_submit,
        })
        from ray_tpu._private import tracing

        t = tracing.tracer()
        if t is None or req.trace is None:
            return
        ctx = tracing.extract(req.trace)
        if ctx is None:
            return
        # Monotonic stamps anchor to the submit wall clock for spans.
        def wall(mono):
            return req.wall_submit + (mono - req.t_submit)

        ok = "ok" if status == FINISHED else "error"
        if req.t_sched is not None:
            t.emit(ctx.trace_id, tracing._new_id(), ctx.span_id,
                   "llm.queue", wall(req.t_submit), queue_s,
                   component="llm", tags={"seq": req.seq_id})
            if req.t_prefill_done is not None:
                t.emit(ctx.trace_id, tracing._new_id(), ctx.span_id,
                       "llm.prefill", wall(req.t_sched), prefill_s,
                       component="llm",
                       tags={"seq": req.seq_id,
                             "cached_tokens": req.cached_prompt_tokens})
                events = []
                if req.t_first_token is not None:
                    events.append([wall(req.t_first_token),
                                   "first_token"])
                t.emit(ctx.trace_id, tracing._new_id(), ctx.span_id,
                       "llm.decode", wall(req.t_prefill_done), decode_s,
                       status=ok, component="llm",
                       tags={"seq": req.seq_id,
                             "tokens": len(req.out_tokens)},
                       events=events)
        else:
            # Never scheduled (shed/cancelled in the waitqueue).
            t.emit(ctx.trace_id, tracing._new_id(), ctx.span_id,
                   "llm." + status.lower(), wall(req.t_submit), queue_s,
                   status=ok, component="llm",
                   tags={"seq": req.seq_id})

    # ----------------------------------------------------------------- step
    def step(self) -> bool:
        """Run ONE continuous-batching iteration: admit + one prefill
        chunk per prefilling sequence (under the token budget) + one
        decode for every fully-prefilled sequence. Returns True if any
        work ran. Public so tests/bench can drive deterministically."""
        with self._lock:
            try:
                chunks, decodes = self.scheduler.schedule()
            except MemoryError as e:
                # A single sequence outgrew the pool: fail it, keep going.
                for r in list(self.scheduler.running):
                    self._finish(r, FAILED, KVCacheOOM(str(e)))
                return True
            if not chunks and not decodes:
                # Parked head with nothing running: no future free() can
                # unpark it (submit-time checks bound single requests, but
                # fragmentation from a dead pool must not spin forever).
                if (self.scheduler.queue_depth() > 0
                        and not self.scheduler.running
                        and not self.cache.can_allocate(1)):
                    head = self.scheduler.waiting[0]
                    self.scheduler.remove_waiting(head)
                    self._finish(head, FAILED, KVCacheOOM(
                        "KV pool exhausted with no running sequences to "
                        "free blocks"))
                return False
            if chunks:
                self._run_prefill_chunks(chunks)
            # Newly completed prefills join decode NEXT iteration; their
            # first token came out of the chunk logits.
            if decodes:
                decodes = [r for r in decodes if not r.finished()]
            if decodes:
                self._run_decode(decodes)
            self.num_steps += 1
            return True

    def _run_prefill_chunks(self, chunks: List[Tuple[Request, int, int]]):
        import jax.numpy as jnp

        bs = self.cache.block_size
        b_pad = _pow2_at_least(len(chunks))
        max_chunk = max(n for _, _, n in chunks)
        c_pad = _pow2_at_least(max_chunk)
        tokens = np.zeros((b_pad, c_pad), np.int32)
        starts = np.zeros((b_pad,), np.int32)
        lens = np.ones((b_pad,), np.int32)
        for i, (r, start, n) in enumerate(chunks):
            tokens[i, :n] = r.prompt[start:start + n]
            starts[i] = start
            lens[i] = n
        tables = self.cache.padded_tables([r.seq_id for r, _, _ in chunks])
        # Cover every position this program may touch, including padded
        # chunk tails (their writes must resolve to real table entries
        # or the NULL padding, never clamp onto a live block).
        need_m = max((int(s) + c_pad - 1) // bs + 1
                     for s in starts[:len(chunks)])
        m_pad = _pow2_at_least(max(tables.shape[1], need_m))
        bt = np.zeros((b_pad, m_pad), np.int32)
        bt[:len(chunks), :tables.shape[1]] = tables
        logits, self.cache.data = self._prefill_chunk(
            self.params, self.cache.data, jnp.asarray(tokens),
            jnp.asarray(starts), jnp.asarray(lens), jnp.asarray(bt))
        logits = None if not any(
            start + n >= len(r.prompt) for r, start, n in chunks) \
            else np.asarray(logits)
        completed: List[Request] = []
        rows: List[int] = []
        for i, (r, start, n) in enumerate(chunks):
            self.num_prefill_tokens += n
            r.prefill_pos = start + n
            # Blocks computed so far become shareable immediately — a
            # concurrent same-prefix request hits them mid-prefill.
            self.cache.register_prefix(r.seq_id, r.prefill_pos)
            if r.prefill_pos >= len(r.prompt):
                r.t_prefill_done = time.monotonic()
                completed.append(r)
                rows.append(i)
        if completed:
            self._emit(completed, logits[rows])

    def _run_decode(self, reqs: List[Request]):
        import jax.numpy as jnp

        bs = self.cache.block_size
        b_pad = _pow2_at_least(len(reqs))
        tokens = np.zeros((b_pad,), np.int32)
        positions = np.zeros((b_pad,), np.int32)
        for i, r in enumerate(reqs):
            tokens[i] = r.last_token
            positions[i] = r.num_tokens - 1  # slot this step writes
        tables = self.cache.padded_tables([r.seq_id for r in reqs])
        m_pad = max(_pow2_at_least(tables.shape[1]),
                    (int(positions.max()) // bs) + 1)
        bt = np.zeros((b_pad, m_pad), np.int32)
        bt[:len(reqs), :tables.shape[1]] = tables
        logits, self.cache.data = self._decode(
            self.params, self.cache.data, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(bt))
        self._emit(reqs, np.asarray(logits)[:len(reqs)])

    def _emit(self, reqs: List[Request], logits: np.ndarray):
        """Sample one token per request from its logits row, stream it,
        and retire sequences that hit EOS / their token budget."""
        for i, req in enumerate(reqs):
            tok = self._sample(req, logits[i])
            if req.t_first_token is None:
                req.t_first_token = time.monotonic()
            req.out_tokens.append(tok)
            self.num_generated_tokens += 1
            req.output_queue.put(tok)
            if ((req.eos_token_id is not None and tok == req.eos_token_id)
                    or len(req.out_tokens) >= req.max_new_tokens):
                self._finish(req, FINISHED)

    @staticmethod
    def _sample(req: Request, row: np.ndarray) -> int:
        if req.temperature <= 0.0:
            return int(np.argmax(row))
        # Per-request deterministic sampling stream (seeded, host-side).
        rng = np.random.default_rng(
            (req.seed if req.seed is not None else req.seq_id,
             len(req.out_tokens)))
        z = row.astype(np.float64) / req.temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(rng.choice(len(row), p=p))

    # -------------------------------------------------------------- queries
    def queue_depth(self) -> int:
        return self.scheduler.queue_depth()

    def stats(self) -> Dict[str, Any]:
        out = {
            "engine_id": self.engine_id,
            "tp_size": self.config.tp_size,
            "steps": self.num_steps,
            "prefill_tokens": self.num_prefill_tokens,
            "generated_tokens": self.num_generated_tokens,
            "ttft_decomposition": self.ttft_decomposition(),
        }
        out.update(self.scheduler.stats())
        out.update(self.cache.stats())
        return out

    def ttft_decomposition(self) -> Dict[str, Any]:
        """Percentile rollup of the per-request timing records: where
        TTFT lives (queue wait vs prefill vs decode) on this engine."""
        rows = [r for r in list(self._timings)
                if r["status"] == FINISHED]
        if not rows:
            return {"completed": 0}

        def pct(key, q):
            vals = sorted(r[key] for r in rows if r[key] is not None)
            if not vals:
                return None
            return vals[min(len(vals) - 1, int(len(vals) * q))]

        return {
            "completed": len(rows),
            "queue_p50_s": pct("queue_s", 0.5),
            "queue_p99_s": pct("queue_s", 0.99),
            "prefill_p50_s": pct("prefill_s", 0.5),
            "prefill_p99_s": pct("prefill_s", 0.99),
            "decode_p50_s": pct("decode_s", 0.5),
            "decode_p99_s": pct("decode_s", 0.99),
            "ttft_p50_s": pct("ttft_s", 0.5),
            "ttft_p99_s": pct("ttft_s", 0.99),
        }

    def wait_idle(self, timeout_s: float = 60.0) -> bool:
        """Block until no work remains (tests/bench convenience)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if (not self.scheduler.running
                        and self.scheduler.queue_depth() == 0):
                    return True
            time.sleep(0.002)
        return False
