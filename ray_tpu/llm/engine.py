"""Continuous-batching inference engine over the flagship Transformer
(reference role: vLLM's LLMEngine / Ray Serve LLM's engine actor).

One ``InferenceEngine`` owns a paged KV cache pool (with copy-on-write
shared prefix blocks), a continuous-batching scheduler (with chunked
prefill), and two jitted programs over ``models.transformer``:

- ``prefill_chunk``: prompt slices, padded to a (batch, chunk) bucket,
  write their K/V into their allocated blocks in one program; a slice
  that completes its prompt produces the request's FIRST generated
  token. A prompt whose leading blocks hit the prefix cache starts its
  first chunk at the cached length — the shared tokens are never
  recomputed (``prefill_tokens_saved``). A prompt longer than the
  prefill token budget runs as several chunks across iterations, so
  the running batch's inter-token stall is bounded by one chunk.
- ``decode_step``: every fully-prefilled sequence advances one token
  per iteration in one program — Orca's iteration-level batching, so a
  new request joins the batch at the next step boundary instead of
  waiting for the batch to drain, and a finished sequence leaves it
  (and drops its block refs) immediately.

Tensor parallelism (``EngineConfig.tp_size``): the Megatron recipe from
``parallel/`` grafts onto both programs — per-layer weights column/row
sharded on the tp mesh axis, the KV pool sharded along ``n_kv_heads``
(each chip holds its head shard's blocks; block IDS stay global), GSPMD
inserting the psums — so model + cache scale past one chip while the
host-side scheduler and block manager are unchanged. TP decode is
asserted token-for-token identical to single-device decode.

Padding buckets are powers of two, so the number of distinct compiled
programs is logarithmic in the caps. Padded rows aim at the NULL block
and their logits are ignored; because attention masks every slot past a
sequence's context length, a sequence's tokens are IDENTICAL whatever
batch it happened to share an iteration with — the engine's
concurrent-equals-sequential parity test pins exactly that.

Requests stream: ``generate()`` yields token ids as iterations commit
them (time-to-first-token ≈ one prefill — one TAIL chunk when the
prefix cache hits), and closing the consumer (``GeneratorExit``)
cancels the sequence — its private blocks return to the pool
immediately (shared prefix blocks stay with their other holders),
unblocking parked admissions. The engine is thread-safe; a Serve
replica drives it from concurrent streaming handlers with no extra
locking.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
import weakref
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ray_tpu.llm.kv_cache import KVCacheOOM, PagedKVCache  # noqa: F401
from ray_tpu.exceptions import RequestSheddedError
from ray_tpu.llm.scheduler import (
    CANCELLED,
    FAILED,
    FINISHED,
    SHED,
    EngineQueueFull,
    Request,
    Scheduler,
)

__all__ = ["EngineConfig", "InferenceEngine", "live_engines"]

_DONE = "__done__"
_ERROR = "__error__"

# Live engines in this process, for util/state + the dashboard (weak:
# observability must never keep a dead engine's KV pool alive).
_ENGINES: "weakref.WeakValueDictionary[int, InferenceEngine]" = \
    weakref.WeakValueDictionary()
_engine_ids = iter(range(1, 1 << 62))


def live_engines() -> List["InferenceEngine"]:
    """Engines constructed in this process and not yet GC'd (shutdown
    engines remain listed until collected — their final counters are
    still readable)."""
    return [e for _, e in sorted(_ENGINES.items())]


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Engine knobs. ``model`` is the flagship TransformerConfig; the
    KV pool holds ``num_blocks`` blocks of ``block_size`` tokens each
    (block 0 reserved), shared by every live sequence."""

    model: Any = None                  # models.TransformerConfig
    num_blocks: int = 128
    block_size: int = 16
    max_num_seqs: int = 8              # iteration batch cap
    prefill_token_budget: int = 2048   # prompt tokens computed per step
    max_queued_requests: int = 64      # bounded waitqueue (admission)
    eos_token_id: Optional[int] = None
    max_new_tokens_default: int = 64
    param_seed: int = 0
    cache_dtype: Any = None            # default: model dtype
    enable_prefix_caching: bool = True  # COW shared prefix blocks
    tp_size: int = 1                   # tensor-parallel mesh width
    # Speculative decoding: a small DRAFT model proposes spec_k tokens
    # per iteration and the flagship verifies them in ONE multi-token
    # step (models.verify_step). spec_k=0 or draft_model=None disables
    # it (vanilla decode). The draft's KV rides the same block tables
    # as an aux pool. Greedy-only: a decode round containing any
    # temperature>0 sequence falls back to vanilla for that round.
    spec_k: int = 0
    draft_model: Any = None            # draft TransformerConfig

    def resolved_model(self):
        if self.model is not None:
            return self.model
        from ray_tpu.models import TransformerConfig

        return TransformerConfig()


def _pow2_at_least(n: int, floor: int = 1) -> int:
    m = max(int(n), floor)
    p = 1
    while p < m:
        p *= 2
    return p


class InferenceEngine:
    """See module docstring. Construct with real ``params`` or let the
    engine init them from ``param_seed`` (every Serve replica of one
    deployment then serves identical weights with zero shipping)."""

    def __init__(self, config: Optional[EngineConfig] = None,
                 params: Optional[dict] = None,
                 draft_params: Optional[dict] = None):
        import jax
        from functools import partial

        from ray_tpu.models import (
            decode_step,
            init_params,
            prefill_chunk,
            verify_step,
        )

        self.config = config or EngineConfig()
        self.model_cfg = self.config.resolved_model()
        if params is None:
            params = init_params(
                self.model_cfg, jax.random.PRNGKey(self.config.param_seed))
        self.mesh = None
        rules = None
        if self.config.tp_size > 1:
            self.mesh, rules = self._build_tp_mesh(self.config.tp_size)
            params = self._shard_params(params, rules)
        self.params = params
        self.cache = PagedKVCache(
            self.model_cfg, self.config.num_blocks, self.config.block_size,
            dtype=self.config.cache_dtype,
            enable_prefix_caching=self.config.enable_prefix_caching,
            mesh=self.mesh, rules=rules)
        self.scheduler = Scheduler(
            self.cache,
            max_num_seqs=self.config.max_num_seqs,
            prefill_token_budget=self.config.prefill_token_budget,
            max_queued_requests=self.config.max_queued_requests)
        # Donation rewrites the cache in place on accelerators; the CPU
        # backend only warns, so skip it there to keep logs clean.
        backend = jax.default_backend()
        donate = (1,) if backend != "cpu" else ()
        self._prefill_chunk = jax.jit(
            partial(prefill_chunk, self.model_cfg, mesh=self.mesh,
                    rules=rules),
            donate_argnums=donate)
        self._decode = jax.jit(
            partial(decode_step, self.model_cfg, mesh=self.mesh,
                    rules=rules),
            donate_argnums=donate)
        # Speculative decoding: jit the draft's prefill/decode and the
        # flagship's multi-token verify; the draft KV pool attaches to
        # the SAME block manager as an aux pool (one table, two pools).
        self._spec_armed = (self.config.spec_k > 0
                            and self.config.draft_model is not None)
        if self._spec_armed:
            if self.config.tp_size > 1:
                raise ValueError(
                    "speculative decoding is not supported with tp_size "
                    "> 1 (the draft aux pool is unsharded)")
            self.draft_cfg = self.config.draft_model
            if draft_params is None:
                draft_params = init_params(
                    self.draft_cfg,
                    jax.random.PRNGKey(self.config.param_seed + 1))
            self.draft_params = draft_params
            self.cache.attach_aux("draft", self.draft_cfg,
                                  dtype=self.config.cache_dtype)
            self._draft_prefill = jax.jit(
                partial(prefill_chunk, self.draft_cfg),
                donate_argnums=donate)
            self._draft_decode = jax.jit(
                partial(decode_step, self.draft_cfg),
                donate_argnums=donate)
            self._verify = jax.jit(
                partial(verify_step, self.model_cfg, mesh=self.mesh,
                        rules=rules),
                donate_argnums=donate)
        self._lock = threading.RLock()          # scheduler + cache + step
        self._work = threading.Event()          # submit -> loop wakeup
        self._stop = threading.Event()
        self._loop_thread: Optional[threading.Thread] = None
        self._requests: Dict[int, Request] = {}
        # Held-after-prefill sequences (disagg prefill pool): finished
        # requests whose KV blocks stay allocated for p2p export until
        # release_held() (decode-side ack) or the publish TTL fires.
        self._held: Dict[int, Request] = {}
        # -- counters --
        self.num_steps = 0
        self.num_prefill_tokens = 0      # prompt tokens actually computed
        self.num_generated_tokens = 0
        # -- speculative-decoding counters --
        self.spec_rounds = 0             # verify steps run
        self.spec_proposed = 0           # draft tokens proposed
        self.spec_accepted = 0           # proposals the flagship accepted
        self.spec_emitted = 0            # tokens emitted by spec rounds
        self.spec_fallback_rounds = 0    # rounds vanilla-decoded instead
        # Per-request TTFT decomposition records (queue/prefill/decode/
        # ttft seconds), bounded: stats() serves percentile rollups —
        # the elastic episode's "where does TTFT live" evidence.
        from collections import deque as _deque

        self._timings: "_deque" = _deque(maxlen=2048)
        self.engine_id = next(_engine_ids)
        _ENGINES[self.engine_id] = self
        # Flight-recorder section: this engine's waitqueue depth, KV
        # occupancy, and TTFT decomposition render into every debug
        # bundle (weak-registered — a GC'd engine stops reporting via
        # the WeakValueDictionary, and stats() raising on a dead engine
        # is caught per-section at dump time).
        from ray_tpu._private import flight as _flight

        if _flight.active():
            eid = self.engine_id

            def _section(_id=eid):
                e = _ENGINES.get(_id)
                return e.stats() if e is not None else {"gone": True}

            _flight.add_section(f"llm.engine-{eid}", _section)

    # ------------------------------------------------------ tensor parallel
    @staticmethod
    def _build_tp_mesh(tp: int):
        """A tp-only mesh over the first ``tp`` devices (the standard
        framework axes, every other axis size 1, so the default
        ShardingRules apply unchanged — batch axes become no-op
        shards)."""
        import os

        import jax

        from ray_tpu.parallel.mesh import MeshConfig, make_mesh
        from ray_tpu.parallel.sharding import ShardingRules

        platform = os.environ.get("RAY_TPU_PLATFORM")
        devices = jax.devices(platform) if platform else jax.devices()
        if len(devices) < tp:
            raise ValueError(
                f"tp_size {tp} exceeds {len(devices)} visible devices")
        mesh = make_mesh(MeshConfig(dp=1, fsdp=1, pp=1, tp=tp, sp=1, ep=1),
                         devices=devices[:tp])
        return mesh, ShardingRules()

    def _shard_params(self, params, rules):
        cfg = self.model_cfg
        if cfg.n_heads % self.config.tp_size or \
                cfg.n_kv_heads % self.config.tp_size:
            raise ValueError(
                f"n_heads {cfg.n_heads} / n_kv_heads {cfg.n_kv_heads} "
                f"must divide tp_size {self.config.tp_size}")
        from ray_tpu.models import param_specs
        from ray_tpu.parallel.sharding import shard_params

        return shard_params(params, self.mesh, param_specs(cfg, rules))

    # ------------------------------------------------------------ lifecycle
    def _ensure_loop(self):
        if self._loop_thread is None or not self._loop_thread.is_alive():
            self._loop_thread = threading.Thread(
                target=self._loop, daemon=True, name="llm-engine-step")
            self._loop_thread.start()

    def shutdown(self):
        self._stop.set()
        with self._lock:
            for req in list(self._requests.values()):
                if not req.finished():
                    # Remove from the waitqueue BEFORE finishing: a loop
                    # thread already past its stop-check blocks on this
                    # lock and would otherwise re-admit the CANCELLED
                    # request (reallocating blocks, streaming past DONE).
                    self.scheduler.remove_waiting(req)
                    self._finish(req, CANCELLED)
            for seq_id in list(self._held):
                self.release_held(seq_id)
        self._work.set()

    def _loop(self):
        while not self._stop.is_set():
            self._work.wait()
            if self._stop.is_set():
                return
            try:
                busy = self.step()
            except Exception as exc:  # noqa: BLE001 — engine must not die
                # An unexpected step failure (compile error, device OOM)
                # must not strand consumers on a dead loop thread: fail
                # every in-flight request TYPED (freeing its blocks) and
                # keep serving — the next submit sees a clean engine.
                with self._lock:
                    for req in list(self._requests.values()):
                        if not req.finished():
                            self.scheduler.remove_waiting(req)
                            self._finish(req, FAILED, exc)
                busy = True
                continue
            if not busy:
                idle = False
                with self._lock:
                    # Check + clear under the submit lock: a concurrent
                    # submit either lands before the check (not idle) or
                    # blocks until after the clear and re-sets the event.
                    if (not self.scheduler.running
                            and self.scheduler.queue_depth() == 0):
                        self._work.clear()
                        idle = True
                if not idle:
                    # Defensive: a non-admittable queue must not busy-spin.
                    time.sleep(0.001)

    # -------------------------------------------------------------- request
    def submit(self, prompt: List[int],
               max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               temperature: float = 0.0,
               seed: Optional[int] = None,
               priority: int = 0,
               trace=None,
               hold_after_prefill: bool = False) -> Request:
        """Enqueue a request. Past the bounded waitqueue the LOWEST
        priority class loses: either this submit raises
        ``EngineQueueFull`` (a ``RequestSheddedError``) or a worse
        already-waiting request is evicted with a typed
        ``RequestSheddedError`` on its stream — overload degrades by
        policy, not by timeout. Tokens arrive on ``req.output_queue``
        as iterations commit them."""
        req = Request(
            prompt,
            max_new_tokens if max_new_tokens is not None
            else self.config.max_new_tokens_default,
            eos_token_id=(eos_token_id if eos_token_id is not None
                          else self.config.eos_token_id),
            temperature=temperature, seed=seed, priority=priority)
        req.trace = trace
        req.hold_after_prefill = bool(hold_after_prefill)
        # Reject what can NEVER be served: a completion longer than the
        # model's context window, or one larger than the whole pool.
        # (Prompts over the prefill token budget are FINE — chunked
        # prefill spreads them across iterations.)
        total = len(req.prompt) + req.max_new_tokens
        max_len = getattr(self.model_cfg, "max_seq_len", None)
        if max_len is not None and total > max_len:
            raise ValueError(
                f"prompt + max_new_tokens = {total} exceeds the model's "
                f"max_seq_len {max_len}")
        if self.cache.blocks_for_tokens(total) > self.cache.usable_blocks:
            raise KVCacheOOM(
                f"request needs {self.cache.blocks_for_tokens(total)} "
                f"blocks for {total} tokens; pool holds "
                f"{self.cache.usable_blocks}")
        with self._lock:
            victim = self.scheduler.submit(req)
            if victim is not None:
                # Evicted pre-admission (never held blocks): its consumer
                # gets the typed shed error, counted apart from failures.
                self._finish(victim, SHED, RequestSheddedError(
                    f"request (priority class {victim.priority}) evicted "
                    f"from the waitqueue by a class-{req.priority} "
                    f"arrival under overload",
                    priority=victim.priority))
            self._requests[req.seq_id] = req
            self._work.set()
        self._ensure_loop()
        return req

    def generate(self, prompt: List[int],
                 max_new_tokens: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0,
                 seed: Optional[int] = None,
                 priority: int = 0,
                 timeout_s: float = 120.0,
                 trace=None) -> Iterator[int]:
        """Streaming generator of token ids. Closing it mid-generation
        (``close()`` / GC / a Serve stream cancel) frees the sequence's
        private KV blocks immediately."""
        req = self.submit(prompt, max_new_tokens=max_new_tokens,
                          eos_token_id=eos_token_id,
                          temperature=temperature, seed=seed,
                          priority=priority, trace=trace)
        try:
            while True:
                try:
                    item = req.output_queue.get(timeout=timeout_s)
                except queue.Empty:
                    raise TimeoutError(
                        f"no token for {timeout_s}s (sequence "
                        f"{req.seq_id}, status {req.status})") from None
                if isinstance(item, tuple):
                    kind, payload = item
                    if kind == _DONE:
                        return
                    raise payload  # _ERROR
                yield item
        finally:
            if not req.finished():
                self.cancel(req)

    def cancel(self, req) -> bool:
        """Cancel by Request or seq_id: removes it from the waitqueue or
        the running set and drops its block refs NOW."""
        with self._lock:
            if isinstance(req, int):
                req = self._requests.get(req)
            if req is None or req.finished():
                return False
            self.scheduler.remove_waiting(req)
            self._finish(req, CANCELLED)
        self._work.set()  # a parked admission may now fit
        return True

    def _finish(self, req: Request, status: str,
                error: Optional[BaseException] = None):
        self.scheduler.release(req, status, error)
        self._requests.pop(req.seq_id, None)
        req.t_finish = time.monotonic()
        self._record_timing(req, status)
        if status in (FAILED, SHED) and error is not None:
            req.output_queue.put((_ERROR, error))
        else:
            req.output_queue.put((_DONE, status))

    def _hold(self, req: Request):
        """Disagg prefill pool: retire a ``hold_after_prefill`` request
        WITHOUT freeing its KV blocks — they stay allocated (and
        prefix-registered) for p2p export until ``release_held`` (the
        decode side's ack) or the publish TTL sweeps them. Consumer-
        visible stream behavior is identical to ``_finish``."""
        self.scheduler.release(req, FINISHED, free_blocks=False)
        self._requests.pop(req.seq_id, None)
        self._held[req.seq_id] = req
        req.t_finish = time.monotonic()
        self._record_timing(req, FINISHED)
        req.output_queue.put((_DONE, FINISHED))

    def release_held(self, seq_id: int) -> int:
        """Free a held sequence's blocks (decode-side ack, TTL expiry,
        or shutdown). Idempotent — ack and the TTL sweep may race; the
        loser sees 0. Returns blocks actually freed."""
        with self._lock:
            if self._held.pop(seq_id, None) is None:
                return 0
            freed = self.cache.free(seq_id)
        self._work.set()  # a parked admission may now fit
        return freed

    def held_count(self) -> int:
        with self._lock:
            return len(self._held)

    # ------------------------------------------------------ disagg adoption
    def begin_adopted(self, prompt: List[int],
                      max_new_tokens: Optional[int] = None,
                      eos_token_id: Optional[int] = None,
                      temperature: float = 0.0,
                      seed: Optional[int] = None,
                      priority: int = 0,
                      trace=None) -> Optional[Request]:
        """Disagg decode pool, step 1 of 3: allocate the prompt's block
        table as admission would (sharing every prefix-cached leading
        block) so a prefill replica's exported KV can be grafted into
        it. Returns None when the batch or pool has no room RIGHT NOW —
        adoption is an optimization, never a queueing state; the caller
        falls back to the colocated path. The returned request is
        cancellable and shutdown-safe like any other, but runs only
        after ``commit_adopted``."""
        req = Request(
            prompt,
            max_new_tokens if max_new_tokens is not None
            else self.config.max_new_tokens_default,
            eos_token_id=(eos_token_id if eos_token_id is not None
                          else self.config.eos_token_id),
            temperature=temperature, seed=seed, priority=priority)
        req.trace = trace
        total = len(req.prompt) + req.max_new_tokens
        max_len = getattr(self.model_cfg, "max_seq_len", None)
        if max_len is not None and total > max_len:
            return None
        with self._lock:
            if len(self.scheduler.running) >= self.config.max_num_seqs:
                return None
            cached = self.cache.allocate_prefix(
                req.seq_id, req.prompt, extra_tokens=1)
            if cached is None:
                return None
            req.cached_prompt_tokens = cached
            req.t_sched = time.monotonic()
            self._requests[req.seq_id] = req
        return req

    def abort_adopted(self, req: Request) -> None:
        """Undo ``begin_adopted`` (the remote prefill or the p2p pull
        failed): drop the allocation and forget the request. The caller
        retries on the colocated path with a FRESH submit."""
        with self._lock:
            self._requests.pop(req.seq_id, None)
            self.cache.free(req.seq_id)
        self._work.set()

    def adopt_kv(self, req: Request, payload: dict) -> bool:
        """Disagg step 2: graft the prefill replica's exported blocks
        into this pool under the adopted sequence's table. Blocks
        before the locally prefix-cached boundary are NEVER written
        (they are shared with their other holders); the payload must
        cover everything from that boundary on or the graft is refused
        (False — the shipping plan went stale, caller falls back). On
        success the full prompt registers in the prefix cache and the
        transfer phase stamp closes."""
        graft_from = req.cached_prompt_tokens // self.cache.block_size
        if (int(payload.get("block_size", -1)) != self.cache.block_size
                or int(payload.get("start_block", 0)) > graft_from):
            return False
        with self._lock:
            try:
                self.cache.graft_blocks(req.seq_id, payload,
                                        start_block=graft_from)
            except (KeyError, ValueError):
                return False
            self.cache.register_prefix(req.seq_id, len(req.prompt))
        nbytes = 0
        for part in (payload, *payload.get("aux", {}).values()):
            for name in ("k", "v"):
                arr = part.get(name)
                if arr is not None:
                    nbytes += int(getattr(arr, "nbytes", 0))
        req.kv_ship = (int(payload.get("blocks", 0)), nbytes)
        now = time.monotonic()
        if req.t_prefill_done is None:
            # The caller normally stamps this when the remote prefill
            # RPC returns; backfill keeps transfer_s >= 0 regardless.
            req.t_prefill_done = now
        req.t_transfer_done = now
        return True

    def commit_adopted(self, req: Request, first_token: int) -> None:
        """Disagg step 3: the grafted sequence becomes a live decode
        row. Streams the prefill replica's first token (sampled there
        from the final chunk's logits — identical to the colocated
        path) and joins the running set at the decode phase; EOS or a
        1-token budget finishes immediately."""
        tok = int(first_token)
        with self._lock:
            now = time.monotonic()
            if req.t_prefill_done is None:
                req.t_prefill_done = now
            if req.t_transfer_done is None:
                req.t_transfer_done = now
            req.prefill_pos = len(req.prompt)
            req.t_first_token = now
            req.out_tokens.append(tok)
            self.num_generated_tokens += 1
            req.output_queue.put(tok)
            if ((req.eos_token_id is not None
                    and tok == req.eos_token_id)
                    or len(req.out_tokens) >= req.max_new_tokens):
                self._finish(req, FINISHED)
                return
            self.scheduler.adopt_running(req)
            self._work.set()
        self._ensure_loop()

    def _record_timing(self, req: Request, status: str):
        """TTFT decomposition record + (when the request carried a trace
        context) llm.queue / llm.prefill / llm.decode spans with a
        first_token event — the per-request waterfall's engine rows."""
        t_end = req.t_finish
        queue_s = ((req.t_sched - req.t_submit)
                   if req.t_sched is not None else t_end - req.t_submit)
        prefill_s = ((req.t_prefill_done - req.t_sched)
                     if req.t_sched is not None
                     and req.t_prefill_done is not None else 0.0)
        # Disagg-adopted sequences add a TRANSFER phase (p2p KV pull +
        # graft) between prefill and decode; colocated requests have
        # none and their decode starts at t_prefill_done.
        transfer_s = ((req.t_transfer_done - req.t_prefill_done)
                      if req.t_transfer_done is not None
                      and req.t_prefill_done is not None else 0.0)
        t_decode0 = (req.t_transfer_done
                     if req.t_transfer_done is not None
                     else req.t_prefill_done)
        decode_s = (t_end - t_decode0) if t_decode0 is not None else 0.0
        self._timings.append({
            "status": status,
            "queue_s": queue_s,
            "prefill_s": prefill_s,
            "transfer_s": transfer_s,
            "decode_s": decode_s,
            "ttft_s": ((req.t_first_token - req.t_submit)
                       if req.t_first_token is not None else None),
            "total_s": t_end - req.t_submit,
        })
        from ray_tpu._private import tracing

        t = tracing.tracer()
        if t is None or req.trace is None:
            return
        ctx = tracing.extract(req.trace)
        if ctx is None:
            return
        # Monotonic stamps anchor to the submit wall clock for spans.
        def wall(mono):
            return req.wall_submit + (mono - req.t_submit)

        ok = "ok" if status == FINISHED else "error"
        if req.t_sched is not None:
            t.emit(ctx.trace_id, tracing._new_id(), ctx.span_id,
                   "llm.queue", wall(req.t_submit), queue_s,
                   component="llm", tags={"seq": req.seq_id})
            if req.t_prefill_done is not None:
                t.emit(ctx.trace_id, tracing._new_id(), ctx.span_id,
                       "llm.prefill", wall(req.t_sched), prefill_s,
                       component="llm",
                       tags={"seq": req.seq_id,
                             "cached_tokens": req.cached_prompt_tokens})
                if req.t_transfer_done is not None:
                    blocks, nbytes = req.kv_ship or (0, 0)
                    t.emit(ctx.trace_id, tracing._new_id(), ctx.span_id,
                           "llm.kv_ship", wall(req.t_prefill_done),
                           transfer_s, component="llm",
                           tags={"seq": req.seq_id, "blocks": blocks,
                                 "bytes": nbytes})
                events = []
                if req.t_first_token is not None:
                    events.append([wall(req.t_first_token),
                                   "first_token"])
                t.emit(ctx.trace_id, tracing._new_id(), ctx.span_id,
                       "llm.decode", wall(t_decode0), decode_s,
                       status=ok, component="llm",
                       tags={"seq": req.seq_id,
                             "tokens": len(req.out_tokens)},
                       events=events)
        else:
            # Never scheduled (shed/cancelled in the waitqueue).
            t.emit(ctx.trace_id, tracing._new_id(), ctx.span_id,
                   "llm." + status.lower(), wall(req.t_submit), queue_s,
                   status=ok, component="llm",
                   tags={"seq": req.seq_id})

    # ----------------------------------------------------------------- step
    def step(self) -> bool:
        """Run ONE continuous-batching iteration: admit + one prefill
        chunk per prefilling sequence (under the token budget) + one
        decode for every fully-prefilled sequence. Returns True if any
        work ran. Public so tests/bench can drive deterministically."""
        with self._lock:
            try:
                chunks, decodes = self.scheduler.schedule()
            except MemoryError as e:
                # A single sequence outgrew the pool: fail it, keep going.
                for r in list(self.scheduler.running):
                    self._finish(r, FAILED, KVCacheOOM(str(e)))
                return True
            if not chunks and not decodes:
                # Parked head with nothing running: no future free() can
                # unpark it (submit-time checks bound single requests, but
                # fragmentation from a dead pool must not spin forever).
                if (self.scheduler.queue_depth() > 0
                        and not self.scheduler.running
                        and not self.cache.can_allocate(1)):
                    head = self.scheduler.waiting[0]
                    self.scheduler.remove_waiting(head)
                    self._finish(head, FAILED, KVCacheOOM(
                        "KV pool exhausted with no running sequences to "
                        "free blocks"))
                return False
            if chunks:
                self._run_prefill_chunks(chunks)
            # Newly completed prefills join decode NEXT iteration; their
            # first token came out of the chunk logits.
            if decodes:
                decodes = [r for r in decodes if not r.finished()]
            if decodes:
                if self._spec_armed:
                    self._run_spec_decode(decodes)
                else:
                    self._run_decode(decodes)
            self.num_steps += 1
            return True

    def _run_prefill_chunks(self, chunks: List[Tuple[Request, int, int]]):
        import jax.numpy as jnp

        bs = self.cache.block_size
        b_pad = _pow2_at_least(len(chunks))
        max_chunk = max(n for _, _, n in chunks)
        c_pad = _pow2_at_least(max_chunk)
        tokens = np.zeros((b_pad, c_pad), np.int32)
        starts = np.zeros((b_pad,), np.int32)
        lens = np.ones((b_pad,), np.int32)
        for i, (r, start, n) in enumerate(chunks):
            tokens[i, :n] = r.prompt[start:start + n]
            starts[i] = start
            lens[i] = n
        tables = self.cache.padded_tables([r.seq_id for r, _, _ in chunks])
        # Cover every position this program may touch, including padded
        # chunk tails (their writes must resolve to real table entries
        # or the NULL padding, never clamp onto a live block).
        need_m = max((int(s) + c_pad - 1) // bs + 1
                     for s in starts[:len(chunks)])
        m_pad = _pow2_at_least(max(tables.shape[1], need_m))
        bt = np.zeros((b_pad, m_pad), np.int32)
        bt[:len(chunks), :tables.shape[1]] = tables
        logits, self.cache.data = self._prefill_chunk(
            self.params, self.cache.data, jnp.asarray(tokens),
            jnp.asarray(starts), jnp.asarray(lens), jnp.asarray(bt))
        if self._spec_armed:
            # The draft's KV rides the SAME chunk plan into its aux
            # pool — after prefill both models hold the prompt's cache
            # and the first spec round can draft immediately.
            _, draft_data = self._draft_prefill(
                self.draft_params, self.cache.aux_data("draft"),
                jnp.asarray(tokens), jnp.asarray(starts),
                jnp.asarray(lens), jnp.asarray(bt))
            self.cache.set_aux_data("draft", draft_data)
        logits = None if not any(
            start + n >= len(r.prompt) for r, start, n in chunks) \
            else np.asarray(logits)
        completed: List[Request] = []
        rows: List[int] = []
        for i, (r, start, n) in enumerate(chunks):
            self.num_prefill_tokens += n
            r.prefill_pos = start + n
            # Blocks computed so far become shareable immediately — a
            # concurrent same-prefix request hits them mid-prefill.
            self.cache.register_prefix(r.seq_id, r.prefill_pos)
            if r.prefill_pos >= len(r.prompt):
                r.t_prefill_done = time.monotonic()
                completed.append(r)
                rows.append(i)
        if completed:
            self._emit(completed, logits[rows])

    def _run_decode(self, reqs: List[Request]):
        import jax.numpy as jnp

        bs = self.cache.block_size
        b_pad = _pow2_at_least(len(reqs))
        tokens = np.zeros((b_pad,), np.int32)
        positions = np.zeros((b_pad,), np.int32)
        for i, r in enumerate(reqs):
            tokens[i] = r.last_token
            positions[i] = r.num_tokens - 1  # slot this step writes
        tables = self.cache.padded_tables([r.seq_id for r in reqs])
        m_pad = max(_pow2_at_least(tables.shape[1]),
                    (int(positions.max()) // bs) + 1)
        bt = np.zeros((b_pad, m_pad), np.int32)
        bt[:len(reqs), :tables.shape[1]] = tables
        logits, self.cache.data = self._decode(
            self.params, self.cache.data, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(bt))
        self._emit(reqs, np.asarray(logits)[:len(reqs)])

    def _run_spec_decode(self, reqs: List[Request]):
        """One SPECULATIVE round: the draft proposes ``spec_k`` greedy
        tokens per sequence (its KV riding the shared block tables in
        the aux pool), the flagship scores ``[last_token, d_1..d_k]``
        in ONE ``verify_step``, and the longest agreeing prefix plus
        one bonus token from the verify logits commits — 1 to k+1
        tokens per sequence per iteration, token-for-token identical
        to vanilla greedy decode (the flagship's argmax is always the
        authority; the draft only picks how many positions one step
        scores).

        Fallback to a vanilla round (counted) when any row samples at
        temperature > 0 (spec is greedy-only) or the k lookahead slots
        don't all allocate. Stale lookahead KV past an accepted prefix
        is masked by context length until the NEXT round's writes —
        which always cover it — land (see ``verify_step``)."""
        k = self.config.spec_k
        if any(r.temperature > 0.0 for r in reqs):
            self.spec_fallback_rounds += 1
            return self._run_decode(reqs)
        # schedule() guaranteed position num_tokens-1 (+1 headroom);
        # verify also writes num_tokens .. num_tokens+k-1.
        for r in reqs:
            for pos in range(r.num_tokens, r.num_tokens + k):
                if not self.cache.ensure_slot(r.seq_id, pos):
                    self.spec_fallback_rounds += 1
                    return self._run_decode(reqs)
        import jax.numpy as jnp

        bs = self.cache.block_size
        b = len(reqs)
        b_pad = _pow2_at_least(b)
        c_pad = _pow2_at_least(k + 1)
        tables = self.cache.padded_tables([r.seq_id for r in reqs])
        # Cover every position verify's padded columns may touch —
        # block lookups CLAMP to the last table column, so positions
        # past a row's real table must resolve to the zero (NULL) pad,
        # never onto its last live block.
        need_m = max((r.num_tokens - 1 + c_pad - 1) // bs + 1
                     for r in reqs)
        m_pad = _pow2_at_least(max(tables.shape[1], need_m))
        bt = np.zeros((b_pad, m_pad), np.int32)
        bt[:b, :tables.shape[1]] = tables
        bt_j = jnp.asarray(bt)

        # Draft pass: k sequential one-token steps over the aux pool.
        draft_data = self.cache.aux_data("draft")
        proposals = np.zeros((b, k), np.int32)
        cur = np.zeros((b_pad,), np.int32)
        pos = np.zeros((b_pad,), np.int32)
        for i, r in enumerate(reqs):
            cur[i] = r.last_token
        for j in range(k):
            for i, r in enumerate(reqs):
                pos[i] = r.num_tokens - 1 + j
            logits, draft_data = self._draft_decode(
                self.draft_params, draft_data, jnp.asarray(cur),
                jnp.asarray(pos), bt_j)
            nxt = np.argmax(np.asarray(logits)[:b], axis=-1)
            proposals[:, j] = nxt
            cur[:b] = nxt
        self.cache.set_aux_data("draft", draft_data)

        # Verify pass: one flagship step scores all k proposals.
        vtok = np.zeros((b_pad, c_pad), np.int32)
        starts = np.zeros((b_pad,), np.int32)
        for i, r in enumerate(reqs):
            vtok[i, 0] = r.last_token
            vtok[i, 1:k + 1] = proposals[i]
            starts[i] = r.num_tokens - 1
        logits, self.cache.data = self._verify(
            self.params, self.cache.data, jnp.asarray(vtok),
            jnp.asarray(starts), bt_j)
        logits = np.asarray(logits)[:b, :k + 1]

        self.spec_rounds += 1
        self.spec_proposed += b * k
        for i, req in enumerate(reqs):
            row = logits[i]
            accepted = 0
            while accepted < k and int(np.argmax(row[accepted])) \
                    == int(proposals[i, accepted]):
                accepted += 1
            self.spec_accepted += accepted
            # Accepted proposals + one bonus token (the flagship's own
            # next token after the accepted prefix) — exactly what
            # sequential greedy decode would have produced.
            toks = [int(proposals[i, j]) for j in range(accepted)]
            toks.append(int(np.argmax(row[accepted])))
            if req.t_first_token is None:
                req.t_first_token = time.monotonic()
            for tok in toks:
                req.out_tokens.append(tok)
                self.num_generated_tokens += 1
                self.spec_emitted += 1
                req.output_queue.put(tok)
                if ((req.eos_token_id is not None
                        and tok == req.eos_token_id)
                        or len(req.out_tokens) >= req.max_new_tokens):
                    if req.hold_after_prefill:
                        self._hold(req)
                    else:
                        self._finish(req, FINISHED)
                    break

    def _emit(self, reqs: List[Request], logits: np.ndarray):
        """Sample one token per request from its logits row, stream it,
        and retire sequences that hit EOS / their token budget."""
        for i, req in enumerate(reqs):
            tok = self._sample(req, logits[i])
            if req.t_first_token is None:
                req.t_first_token = time.monotonic()
            req.out_tokens.append(tok)
            self.num_generated_tokens += 1
            req.output_queue.put(tok)
            if ((req.eos_token_id is not None and tok == req.eos_token_id)
                    or len(req.out_tokens) >= req.max_new_tokens):
                if req.hold_after_prefill:
                    self._hold(req)
                else:
                    self._finish(req, FINISHED)

    @staticmethod
    def _sample(req: Request, row: np.ndarray) -> int:
        if req.temperature <= 0.0:
            return int(np.argmax(row))
        # Per-request deterministic sampling stream (seeded, host-side).
        rng = np.random.default_rng(
            (req.seed if req.seed is not None else req.seq_id,
             len(req.out_tokens)))
        z = row.astype(np.float64) / req.temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(rng.choice(len(row), p=p))

    # -------------------------------------------------------------- queries
    def queue_depth(self) -> int:
        return self.scheduler.queue_depth()

    def stats(self) -> Dict[str, Any]:
        out = {
            "engine_id": self.engine_id,
            "tp_size": self.config.tp_size,
            "steps": self.num_steps,
            "prefill_tokens": self.num_prefill_tokens,
            "generated_tokens": self.num_generated_tokens,
            "ttft_decomposition": self.ttft_decomposition(),
            "held_sequences": len(self._held),
        }
        if self._spec_armed:
            out["spec"] = {
                "k": self.config.spec_k,
                "rounds": self.spec_rounds,
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "emitted": self.spec_emitted,
                "fallback_rounds": self.spec_fallback_rounds,
                "acceptance_rate": (self.spec_accepted
                                    / max(1, self.spec_proposed)),
            }
        out.update(self.scheduler.stats())
        out.update(self.cache.stats())
        return out

    def ttft_decomposition(self) -> Dict[str, Any]:
        """Percentile rollup of the per-request timing records: where
        TTFT lives (queue wait vs prefill vs decode) on this engine."""
        rows = [r for r in list(self._timings)
                if r["status"] == FINISHED]
        if not rows:
            return {"completed": 0}

        def pct(key, q):
            vals = sorted(r[key] for r in rows
                          if r.get(key) is not None)
            if not vals:
                return None
            return vals[min(len(vals) - 1, int(len(vals) * q))]

        return {
            "completed": len(rows),
            "queue_p50_s": pct("queue_s", 0.5),
            "queue_p99_s": pct("queue_s", 0.99),
            "prefill_p50_s": pct("prefill_s", 0.5),
            "prefill_p99_s": pct("prefill_s", 0.99),
            "transfer_p50_s": pct("transfer_s", 0.5),
            "transfer_p99_s": pct("transfer_s", 0.99),
            "decode_p50_s": pct("decode_s", 0.5),
            "decode_p99_s": pct("decode_s", 0.99),
            "ttft_p50_s": pct("ttft_s", 0.5),
            "ttft_p99_s": pct("ttft_s", 0.99),
        }

    def wait_idle(self, timeout_s: float = 60.0) -> bool:
        """Block until no work remains (tests/bench convenience)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if (not self.scheduler.running
                        and self.scheduler.queue_depth() == 0):
                    return True
            time.sleep(0.002)
        return False
