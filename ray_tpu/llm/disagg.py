"""Disaggregated prefill/decode serving (reference role: the P/D
disaggregation tier in modern LLM serving stacks — DistServe/Splitwise-
style pools, vLLM's KV-transfer connectors — rebuilt on this framework's
own primitives: owner-resolved p2p objects for the KV hop, Serve
deployments for the pools, the paged-cache graft path for adoption).

Two pools, one request:

- **prefill pool** (``PrefillLLMServer``): runs chunked prefill only.
  A finished prompt's KV blocks are HELD in the engine (never freed on
  finish), packed per-layer with ``PagedKVCache.export_blocks``, and
  published as ONE owner-resolved p2p object (``ray_tpu.put`` — the
  replica owns the bytes; a decode replica's ``ray_tpu.get`` resolves
  ownership once and pulls peer-to-peer, zero head RPCs in steady
  state). The ticket returned to the pairing layer carries the object
  ref, the first generated token (sampled here from the final chunk's
  logits — deterministic, identical to the colocated path), and the
  publication id. Blocks free on the decode side's ACK, or on a
  bounded TTL (``RAY_TPU_LLM_KV_PUBLISH_TTL_S``) when the ack never
  comes — a crashed decode replica cannot leak prefill-pool KV.
- **decode pool** (``DecodeLLMServer``): allocates the prompt's block
  table (sharing its own cached prefix blocks), pulls the payload p2p,
  grafts it under the table (``adopt_kv``), and joins the sequence to
  its continuous batch at the DECODE phase — no prompt recompute. Any
  failure along that path (publisher died, pull timed out, plan went
  stale) falls back to a transparent LOCAL re-prefill: the request
  always completes, disaggregation is an optimization with a typed
  fallback, never a correctness dependency. Decode replicas may run
  SPECULATIVE decoding (draft model in the engine config) — disagg
  pairs with it unchanged, since adoption ends exactly where decode
  begins.

**Tail-only shipping**: the pairing layer consults the decode pool's
prefix-digest reports (the same telemetry prefix-aware routing uses)
and asks the prefill replica to export only blocks PAST the pool's
cached overlap. The decode replica re-validates against its OWN cache
at graft time; a stale plan is refused and falls back — shared blocks
are never overwritten.

**Per-pool autoscaling**: each pool scales on its own saturation
signal via ``AutoscalingConfig(metric=...)`` — the prefill pool on
engine waitqueue depth (prompts queued behind compute), the decode
pool on KV blocks in use (resident sequences) — instead of one
conflated ongoing-request count.

Wiring::

    pre_app, dec_app = build_disagg_llm_app(
        EngineConfig(model=cfg), prefill_replicas=1, decode_replicas=2)
    serve.run(pre_app); serve.run(dec_app)
    h = DisaggHandle.from_deployments()
    for tok in h.stream({"prompt": [...], "max_new_tokens": 64}): ...
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, Iterator, Optional, Union

import ray_tpu
from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.log import get_logger
from ray_tpu.llm.api import LLMServer
from ray_tpu.llm.engine import EngineConfig

log = get_logger(__name__)

__all__ = ["PrefillLLMServer", "DecodeLLMServer", "DisaggHandle",
           "build_disagg_llm_app"]

_DONE = "__done__"
_ERROR = "__error__"


def _parse(request: Union[Dict[str, Any], list]):
    """(prompt, engine_kwargs, trace) from an LLM request dict/list —
    the same shape ``LLMServer.__call__`` accepts."""
    if isinstance(request, dict):
        prompt = [int(t) for t in request["prompt"]]
        kwargs = {k: request[k] for k in
                  ("max_new_tokens", "eos_token_id", "temperature",
                   "seed", "priority") if k in request}
        trace = request.get("_trace")
    else:
        prompt, kwargs, trace = [int(t) for t in request], {}, None
    return prompt, kwargs, trace


class PrefillLLMServer(LLMServer):
    """Prefill-pool replica: chunked prefill, publish, ack/TTL free.

    ``prefill(request)`` is an RPC (not a stream): it runs the prompt
    through the engine with a ONE-token budget and ``hold_after_prefill``
    (the KV blocks survive the finish), publishes the exported blocks
    p2p, and returns the pairing ticket. ``ack(pub_id)`` frees the
    publication; the TTL sweep frees whatever was never pulled."""

    def __init__(self, engine_config: Optional[EngineConfig] = None,
                 params: Optional[dict] = None,
                 warm_prefix: Optional[list] = None):
        super().__init__(engine_config, params, warm_prefix)
        self._pub_lock = threading.Lock()
        # pub_id (== engine seq_id) -> (deadline_monotonic, blocks)
        self._published: Dict[int, tuple] = {}
        # -- publish/ack lifecycle counters (balance-clean: outstanding
        # is derived, published == acked + expired + outstanding) --
        self.kv_publishes = 0
        self.kv_acks = 0
        self.kv_expiries = 0
        self.kv_blocks_published = 0
        self.kv_blocks_acked = 0
        self.kv_blocks_expired = 0
        self.kv_bytes_published = 0

    def prefill(self, request: Union[Dict[str, Any], list]
                ) -> Dict[str, Any]:
        """Run ONE prompt's chunked prefill and publish its KV. The
        request dict may carry ``_skip_blocks`` (the pairing layer's
        tail-skip plan): leading blocks the decode pool already caches
        are not shipped. Returns the ticket
        ``{"ref", "first_token", "pub_id", "start_block", "blocks",
        "block_size", "bytes"}``."""
        self._expire_published()
        prompt, kwargs, trace = _parse(request)
        skip = 0
        if isinstance(request, dict):
            skip = max(0, int(request.get("_skip_blocks", 0)))
        # The whole completion budget stays on the decode side; here
        # only the first token (from the final chunk's logits) matters.
        kwargs["max_new_tokens"] = 1
        req = self.engine.submit(prompt, trace=trace,
                                 hold_after_prefill=True, **kwargs)
        first: Optional[int] = None
        while True:
            item = req.output_queue.get(
                timeout=float(GlobalConfig.llm_disagg_prefill_timeout_s))
            if isinstance(item, tuple):
                kind, payload = item
                if kind == _ERROR:
                    raise payload
                break
            first = item
        # A prompt whose first token is not held (shed/cancel) never
        # publishes; the typed error above already surfaced it.
        table_len = len(self.engine.cache.table(req.seq_id))
        start_block = min(skip, max(0, table_len - 1))
        payload = self.engine.cache.export_blocks(
            req.seq_id, start_block=start_block)
        ref = ray_tpu.put(payload)
        nbytes = sum(
            int(getattr(part.get(name), "nbytes", 0))
            for part in (payload, *payload.get("aux", {}).values())
            for name in ("k", "v"))
        deadline = time.monotonic() + float(
            GlobalConfig.llm_kv_publish_ttl_s)
        with self._pub_lock:
            self._published[req.seq_id] = (deadline, payload["blocks"])
            self.kv_publishes += 1
            self.kv_blocks_published += payload["blocks"]
            self.kv_bytes_published += nbytes
        return {
            "ref": ref,
            "first_token": first,
            "pub_id": req.seq_id,
            "start_block": payload["start_block"],
            "blocks": payload["blocks"],
            "block_size": payload["block_size"],
            "bytes": nbytes,
        }

    def ack(self, pub_id: int) -> int:
        """Decode-side acknowledgment: the payload was pulled and
        grafted, free the held blocks NOW (instead of at the TTL).
        Idempotent; returns blocks freed."""
        with self._pub_lock:
            ent = self._published.pop(int(pub_id), None)
            if ent is not None:
                self.kv_acks += 1
                self.kv_blocks_acked += ent[1]
        freed = self.engine.release_held(int(pub_id))
        self._expire_published()
        return freed

    def _expire_published(self) -> int:
        """TTL sweep (lazy — runs on prefill/ack/stats, plus the public
        ``expire_published`` hook): free publications never acked by
        their deadline. Zero-leak backstop for dead decode replicas."""
        now = time.monotonic()
        expired = []
        with self._pub_lock:
            for pub_id, (deadline, blocks) in list(
                    self._published.items()):
                if now >= deadline:
                    self._published.pop(pub_id)
                    expired.append((pub_id, blocks))
                    self.kv_expiries += 1
                    self.kv_blocks_expired += blocks
        freed = 0
        for pub_id, _ in expired:
            freed += self.engine.release_held(pub_id)
        return freed

    def expire_published(self) -> int:
        return self._expire_published()

    # ------------------------------------------------- replica telemetry
    def stats(self) -> Dict[str, Any]:
        self._expire_published()
        out = super().stats()
        with self._pub_lock:
            outstanding = len(self._published)
            out.update({
                "kv_publishes": self.kv_publishes,
                "kv_acks": self.kv_acks,
                "kv_expiries": self.kv_expiries,
                "kv_blocks_published": self.kv_blocks_published,
                "kv_blocks_acked": self.kv_blocks_acked,
                "kv_blocks_expired": self.kv_blocks_expired,
                "kv_bytes_published": self.kv_bytes_published,
                "kv_publications_outstanding": outstanding,
            })
        return out


class DecodeLLMServer(LLMServer):
    """Decode-pool replica: adopt remote prefills, stream tokens.

    A request dict carrying ``_disagg`` (the prefill ticket) takes the
    adoption path — pull p2p, graft, join the batch at decode; anything
    failing falls back to a LOCAL re-prefill of the same request. A
    plain request decodes colocated, so the pool also serves as the
    universal fallback target."""

    def __init__(self, engine_config: Optional[EngineConfig] = None,
                 params: Optional[dict] = None,
                 warm_prefix: Optional[list] = None):
        super().__init__(engine_config, params, warm_prefix)
        self.disagg_adopted = 0
        self.disagg_fallbacks = 0

    def __call__(self, request: Union[Dict[str, Any], list]
                 ) -> Iterator[int]:
        if isinstance(request, dict) and request.get("_disagg"):
            yield from self._adopted_stream(request)
            return
        yield from super().__call__(request)

    def _adopted_stream(self, request: Dict[str, Any]) -> Iterator[int]:
        ticket = request["_disagg"]
        prompt, kwargs, trace = _parse(request)
        req = self.engine.begin_adopted(prompt, trace=trace, **kwargs)
        if req is not None:
            # Remote prefill is already done when the ticket lands here;
            # everything from this stamp to the graft is the TRANSFER
            # phase of the TTFT decomposition (llm.kv_ship span).
            req.t_prefill_done = time.monotonic()
            payload = None
            try:
                payload = ray_tpu.get(
                    ticket["ref"],
                    timeout=float(GlobalConfig.llm_disagg_pull_timeout_s))
            except Exception as exc:  # noqa: BLE001 — typed fallback
                log.debug("disagg p2p pull failed (publisher %r): %r — "
                          "re-prefilling locally", ticket.get("pub_id"),
                          exc)
            if (payload is None or ticket.get("first_token") is None
                    or not self.engine.adopt_kv(req, payload)):
                self.engine.abort_adopted(req)
                req = None
        if req is None:
            # Transparent re-prefill: the SAME request runs the plain
            # colocated path on this replica (prefill + decode here).
            self.disagg_fallbacks += 1
            plain = {k: v for k, v in request.items() if k != "_disagg"}
            yield from super().__call__(plain)
            return
        self.disagg_adopted += 1
        self.engine.commit_adopted(req, ticket["first_token"])
        try:
            while True:
                item = req.output_queue.get(timeout=120.0)
                if isinstance(item, tuple):
                    kind, payload = item
                    if kind == _DONE:
                        return
                    raise payload  # _ERROR
                if self.first_token_monotonic is None:
                    self.first_token_monotonic = time.monotonic()
                yield item
        finally:
            # Stream closed mid-generation (client cancel): free the
            # adopted sequence's blocks like any cancelled request.
            if not req.finished():
                self.engine.cancel(req)

    def stats(self) -> Dict[str, Any]:
        out = super().stats()
        out.update({
            "disagg_adopted": self.disagg_adopted,
            "disagg_fallbacks": self.disagg_fallbacks,
        })
        return out


class DisaggHandle:
    """Driver-side pairing layer: one ``stream()`` call = one prefill
    RPC + one decode stream + one exact-publisher ack.

    Plain ``DeploymentHandle`` calls route through the live router, so
    pool autoscaling and dead-replica replacement apply per hop; the
    ack is NOT routed — it goes to the precise replica that published
    (the response's replica binding), because any other replica knows
    nothing about the publication (the TTL covers a lost ack).

    Failure ladder, every rung transparent to the caller:
    prefill RPC fails/times out -> colocated call on the decode pool;
    publisher dies before the pull / pull times out / plan stale ->
    decode replica re-prefills locally; decode replica dies mid-stream
    -> the typed stream error surfaces and a RETRY pairs freshly (the
    chaos matrix pins both)."""

    def __init__(self, prefill_handle, decode_handle,
                 decode_deployment: str = "llm-decode"):
        self._prefill = prefill_handle.options(method_name="prefill",
                                               stream=False)
        self._decode = decode_handle.options(stream=True)
        self._decode_name = decode_deployment
        self.paired = 0
        self.prefill_fallbacks = 0

    @classmethod
    def from_deployments(cls, prefill: str = "llm-prefill",
                         decode: str = "llm-decode") -> "DisaggHandle":
        from ray_tpu import serve

        return cls(serve.get_deployment_handle(prefill),
                   serve.get_deployment_handle(decode),
                   decode_deployment=decode)

    def _plan_skip_blocks(self, prompt, block_size: int) -> int:
        """Tail-skip plan: leading blocks the decode pool's advertised
        prefix caches already hold (capped one short of the full prompt
        — the last prompt position is always recomputed for logits).
        Advisory: the decode replica re-validates at graft time."""
        try:
            from ray_tpu.serve.controller import get_or_create_controller

            rs = get_or_create_controller()._replica_set(
                self._decode_name)
            overlap = rs.plan_prefix(prompt)
        except Exception:  # noqa: BLE001 — plan is best-effort
            return 0
        return min(overlap, len(prompt) - 1) // max(1, block_size)

    def stream(self, request: Union[Dict[str, Any], list]
               ) -> Iterator[int]:
        """Stream one request through the disaggregated pair. Yields
        token ids; the first generated token was computed by the
        prefill pool, every later one by the decode pool."""
        if not isinstance(request, dict):
            request = {"prompt": [int(t) for t in request]}
        ticket = None
        publisher = None
        try:
            resp = self._prefill.remote(dict(request))
            # Capture the serving replica BEFORE result() releases the
            # router slot (and with it the response's replica binding):
            # the ack must reach the exact publisher.
            publisher = resp._replica
            ticket = resp.result(timeout=float(
                GlobalConfig.llm_disagg_prefill_timeout_s))
        except Exception as exc:  # noqa: BLE001 — typed fallback
            log.debug("disagg prefill hop failed: %r — colocated "
                      "fallback on the decode pool", exc)
            ticket, publisher = None, None
        if ticket is None:
            self.prefill_fallbacks += 1
            yield from self._decode.remote(dict(request))
            return
        self.paired += 1
        gen = self._decode.remote({**request, "_disagg": ticket})
        # The first token was minted BY the prefill and rides the
        # ticket: hand it to the client NOW, before the decode hop —
        # client TTFT never waits on a congested decode pool. The
        # decode stream re-emits that token as its first item (adopted:
        # commit_adopted streams it; fallback: the local re-prefill
        # regenerates it), so the first decode item is swallowed as the
        # adoption confirmation instead of re-yielded.
        yield int(ticket["first_token"])
        acked = False
        try:
            for tok in gen:
                if not acked:
                    # First streamed token proves the decode side is
                    # past the graft (or committed to its local
                    # fallback): the publication can free NOW instead
                    # of waiting out the TTL.
                    acked = True
                    self._ack(publisher, ticket["pub_id"])
                    continue  # the prefill-minted token, already out
                yield tok
        finally:
            if not acked:
                # Never got a first token (dead decode replica, caller
                # closed early): still try to free eagerly; the TTL
                # remains the backstop if the publisher is gone too.
                self._ack(publisher, ticket["pub_id"])

    def stream_planned(self, request: Dict[str, Any],
                       block_size: int) -> Iterator[int]:
        """`stream()` with the tail-skip plan computed BEFORE the
        prefill hop (needs the pool's block size up front): the prefill
        replica then ships only the blocks past the decode pool's
        cached overlap."""
        prompt = [int(t) for t in request["prompt"]]
        skip = self._plan_skip_blocks(prompt, block_size)
        yield from self.stream({**request, "_skip_blocks": skip})

    @staticmethod
    def _ack(publisher, pub_id) -> None:
        if publisher is None:
            return
        try:
            publisher.handle_request.remote("ack", (pub_id,), {})
        except Exception:  # noqa: BLE001 — TTL is the backstop
            pass


def build_disagg_llm_app(engine_config: Optional[EngineConfig] = None,
                         *,
                         prefill_name: str = "llm-prefill",
                         decode_name: str = "llm-decode",
                         prefill_replicas: int = 1,
                         decode_replicas: int = 1,
                         prefill_autoscaling: Optional[dict] = None,
                         decode_autoscaling: Optional[dict] = None,
                         max_ongoing_requests: Optional[int] = None,
                         params: Optional[dict] = None,
                         warm_prefix: Optional[list] = None,
                         decode_engine_config: Optional[
                             EngineConfig] = None,
                         ray_actor_options: Optional[dict] = None):
    """Build the (prefill_app, decode_app) pair. Run both with
    ``serve.run`` and pair them with ``DisaggHandle.from_deployments``.

    The prefill pool's engine never speculates (its requests are
    one-token) — a spec-configured ``engine_config`` is stripped to
    vanilla for the prefill deployment and kept (or overridden via
    ``decode_engine_config``) for the decode pool, so one config wires
    both pools AND speculative decoding.

    Per-pool autoscaling defaults: the prefill pool on WAITQUEUE DEPTH
    (prompts parked behind compute), the decode pool on KV BLOCKS IN
    USE (resident sequences) — pass ``*_autoscaling`` dicts (forwarded
    to ``AutoscalingConfig``) to override targets/bounds."""
    from ray_tpu import serve

    engine_config = engine_config or EngineConfig()
    pre_cfg = engine_config
    if pre_cfg.spec_k or pre_cfg.draft_model is not None:
        pre_cfg = dataclasses.replace(pre_cfg, spec_k=0,
                                      draft_model=None)
    dec_cfg = decode_engine_config or engine_config
    if prefill_autoscaling is not None:
        prefill_autoscaling = dict(prefill_autoscaling)
        prefill_autoscaling.setdefault("metric", "queue_depth")
    if decode_autoscaling is not None:
        decode_autoscaling = dict(decode_autoscaling)
        decode_autoscaling.setdefault("metric", "kv_blocks_in_use")
    pre_dep = serve.deployment(
        PrefillLLMServer, name=prefill_name,
        num_replicas=prefill_replicas,
        autoscaling_config=prefill_autoscaling,
        max_ongoing_requests=max_ongoing_requests,
        ray_actor_options=ray_actor_options)
    dec_dep = serve.deployment(
        DecodeLLMServer, name=decode_name,
        num_replicas=decode_replicas,
        autoscaling_config=decode_autoscaling,
        max_ongoing_requests=max_ongoing_requests,
        ray_actor_options=ray_actor_options)
    return (pre_dep.bind(pre_cfg, params, warm_prefix),
            dec_dep.bind(dec_cfg, params, warm_prefix))
