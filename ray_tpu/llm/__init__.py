"""LLM inference library (L11): continuous-batching token serving over
the flagship transformer (reference roles: Ray Serve LLM + vLLM's
engine — Orca iteration-level batching, PagedAttention KV management,
automatic prefix caching, chunked prefill, tensor-parallel decode).

- ``PagedKVCache`` (kv_cache.py): fixed-size blocks in preallocated
  device arrays, per-sequence block tables, refcounted copy-on-write
  SHARED PREFIX BLOCKS (chain-hashed full blocks; a prompt whose prefix
  is cached skips that prefill entirely), cached-free LRU tier, aux
  pools riding the same tables (the spec-decode draft cache), and
  block export/graft for p2p KV shipping.
- ``Scheduler`` (scheduler.py): bounded-waitqueue admission in
  (priority, FIFO) order with LOAD SHEDDING — at capacity the worst
  class is evicted/refused with a typed ``RequestSheddedError`` —
  CHUNKED prefill under the per-iteration token budget (a long prompt
  can't stall the batch), recompute eviction on KV OOM.
- ``InferenceEngine`` (engine.py): jitted chunk-prefill/decode step
  loop with streaming per-request token queues; ``tp_size`` shards the
  model and the KV pool (along ``n_kv_heads``) across the mesh;
  ``spec_k``/``draft_model`` arm SPECULATIVE decoding (draft proposes
  k tokens, the flagship verifies them in one multi-token step —
  greedy output provably identical to vanilla decode).
- ``build_llm_app`` (api.py): Serve deployment builder — token streams
  ride ``handle.options(stream=True)`` / chunked HTTP with per-request
  cancellation propagating to sequence-free; replicas report prefix
  digests the Serve router scores for cache-affinity routing.
- ``build_disagg_llm_app`` (disagg.py): DISAGGREGATED prefill/decode
  pools — prefill replicas publish finished prompts' KV blocks as
  owner-resolved p2p objects (freed on decode-side ack or a bounded
  TTL), decode replicas pull and graft them (tail-only past their own
  cached prefix) and stream tokens; each pool autoscales on its own
  saturation signal.
"""

from ray_tpu.llm.api import LLMServer, build_llm_app
from ray_tpu.llm.disagg import (
    DecodeLLMServer,
    DisaggHandle,
    PrefillLLMServer,
    build_disagg_llm_app,
)
from ray_tpu.llm.engine import EngineConfig, InferenceEngine, live_engines
from ray_tpu.llm.kv_cache import KVCacheOOM, PagedKVCache, chain_digests
from ray_tpu.llm.scheduler import EngineQueueFull, Request, Scheduler

__all__ = [
    "DecodeLLMServer",
    "DisaggHandle",
    "EngineConfig",
    "EngineQueueFull",
    "InferenceEngine",
    "KVCacheOOM",
    "LLMServer",
    "PagedKVCache",
    "PrefillLLMServer",
    "Request",
    "Scheduler",
    "build_disagg_llm_app",
    "build_llm_app",
    "chain_digests",
    "live_engines",
]
