"""LLM inference library (L11): continuous-batching token serving over
the flagship transformer (reference roles: Ray Serve LLM + vLLM's
engine — Orca iteration-level batching, PagedAttention KV management).

- ``PagedKVCache`` (kv_cache.py): fixed-size blocks in preallocated
  device arrays, per-sequence block tables, immediate free/reuse.
- ``Scheduler`` (scheduler.py): bounded-waitqueue admission, prefill
  token budget, recompute eviction on KV OOM.
- ``InferenceEngine`` (engine.py): jitted prefill/decode step loop with
  streaming per-request token queues.
- ``build_llm_app`` (api.py): Serve deployment builder — token streams
  ride ``handle.options(stream=True)`` / chunked HTTP with per-request
  cancellation propagating to sequence-free.
"""

from ray_tpu.llm.api import LLMServer, build_llm_app
from ray_tpu.llm.engine import EngineConfig, InferenceEngine
from ray_tpu.llm.kv_cache import KVCacheOOM, PagedKVCache
from ray_tpu.llm.scheduler import EngineQueueFull, Request, Scheduler

__all__ = [
    "EngineConfig",
    "EngineQueueFull",
    "InferenceEngine",
    "KVCacheOOM",
    "LLMServer",
    "PagedKVCache",
    "Request",
    "Scheduler",
    "build_llm_app",
]
