"""Iteration-level (continuous) batching scheduler (reference role:
Orca's iteration-level scheduling + vLLM's scheduler/policy — admission
from a bounded waitqueue each step, prefill and decode composed per
iteration, eviction-by-recompute on KV OOM).

Per engine iteration ``schedule()`` returns the work for ONE step:

- ``prefills``: requests admitted from the waitqueue this iteration —
  bounded by the prefill token budget (long prompts can't starve the
  decode batch forever), the running-sequence cap, and KV-pool
  headroom. Admission allocates the prompt's blocks; a request that
  doesn't fit PARKS at the head of the queue and is retried every
  iteration (KV-full never crashes, it waits for blocks to free).
- ``decodes``: every running sequence, each guaranteed a physical slot
  for its next token. When the pool is empty mid-decode the YOUNGEST
  running sequence is preempted (blocks freed, request requeued for
  full recompute — vLLM's recompute eviction policy), so the oldest
  work always completes and a long request can never wedge the engine.

Finished/cancelled sequences release their blocks immediately via
``release()`` — freeing is O(1) list work, so a short request parked
behind a long one resumes on the very next iteration.
"""

from __future__ import annotations

import itertools
import queue
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.llm.kv_cache import PagedKVCache

__all__ = ["EngineQueueFull", "Request", "Scheduler",
           "WAITING", "RUNNING", "FINISHED", "CANCELLED", "FAILED"]

WAITING = "WAITING"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
CANCELLED = "CANCELLED"
FAILED = "FAILED"

_seq_counter = itertools.count(1)


class EngineQueueFull(RuntimeError):
    """The bounded admission waitqueue is at capacity (backpressure —
    callers should retry/shed, the engine never buffers unboundedly)."""


class Request:
    """One sequence moving through the engine."""

    def __init__(self, prompt: List[int], max_new_tokens: int,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0,
                 seed: Optional[int] = None):
        if not prompt:
            raise ValueError("empty prompt")
        self.seq_id = next(_seq_counter)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.seed = seed
        self.out_tokens: List[int] = []
        self.status = WAITING
        self.error: Optional[BaseException] = None
        self.preemptions = 0
        # Token stream to the consumer: ints, then one (sentinel, payload).
        self.output_queue: "queue.SimpleQueue" = queue.SimpleQueue()

    # Next position to be computed/written in the KV cache.
    @property
    def num_tokens(self) -> int:
        return len(self.prompt) + len(self.out_tokens)

    @property
    def last_token(self) -> int:
        return self.out_tokens[-1] if self.out_tokens else self.prompt[-1]

    def finished(self) -> bool:
        return self.status in (FINISHED, CANCELLED, FAILED)


class Scheduler:
    """Waitqueue + running set over one PagedKVCache. NOT thread-safe on
    its own — the engine serializes all calls under its step lock."""

    def __init__(self, cache: PagedKVCache, *, max_num_seqs: int = 8,
                 prefill_token_budget: int = 2048,
                 max_queued_requests: int = 64):
        self.cache = cache
        self.max_num_seqs = int(max_num_seqs)
        self.prefill_token_budget = int(prefill_token_budget)
        self.max_queued_requests = int(max_queued_requests)
        self.waiting: "deque[Request]" = deque()
        self.running: List[Request] = []
        self._lock = threading.Lock()  # waitqueue only (submit vs step)
        # -- counters --
        self.num_admitted = 0
        self.num_preempted = 0
        self.park_events = 0  # iterations where KV-full parked admission

    # ------------------------------------------------------------ admission
    def submit(self, req: Request) -> None:
        with self._lock:
            if len(self.waiting) >= self.max_queued_requests:
                raise EngineQueueFull(
                    f"waitqueue at capacity "
                    f"({self.max_queued_requests} requests)")
            self.waiting.append(req)

    def remove_waiting(self, req: Request) -> bool:
        with self._lock:
            try:
                self.waiting.remove(req)
                return True
            except ValueError:
                return False

    def queue_depth(self) -> int:
        with self._lock:
            return len(self.waiting)

    # ------------------------------------------------------------- schedule
    def schedule(self) -> Tuple[List[Request], List[Request]]:
        """Compose one iteration: (prefills admitted now, decode batch).
        Every returned request has cache slots for the tokens this step
        will write."""
        # 1) Guarantee a slot for each running sequence's next token;
        #    evict-on-OOM: preempt the youngest until the rest fit.
        decodes: List[Request] = []
        survivors: List[Request] = []
        for req in self.running:
            if req.finished():
                continue  # release already ran; drop from the set
            survivors.append(req)
        self.running = survivors
        i = 0
        while i < len(self.running):
            req = self.running[i]
            if self.cache.ensure_slot(req.seq_id, req.num_tokens):
                decodes.append(req)
                i += 1
                continue
            victim = self.running[-1]
            if victim is req and len(self.running) == 1:
                # A single sequence that outgrew the whole pool cannot
                # make progress by eviction; fail it loudly.
                raise MemoryError(
                    f"sequence {req.seq_id} needs more KV blocks than "
                    f"the pool holds ({self.cache.usable_blocks})")
            self._preempt(victim)
            decodes = [r for r in decodes if r is not victim]
            # retry the same index (running list shrank behind it)

        # 2) Admit from the waitqueue under the token budget / seq cap /
        #    pool headroom. Stop at the first request that doesn't fit:
        #    FIFO order is the fairness contract (no head-of-line skip).
        prefills: List[Request] = []
        budget = self.prefill_token_budget
        parked = False
        while True:
            with self._lock:
                if not self.waiting:
                    break
                req = self.waiting[0]
                if len(self.running) + len(prefills) >= self.max_num_seqs:
                    break
                # The token budget bounds how much prefill joins ONE
                # iteration, it is not a hard prompt cap: a request may
                # exceed it when admitted alone (preemption-recompute
                # legally grows a prompt past the budget — parking it
                # here forever would wedge the FIFO head; submit() still
                # rejects fresh prompts over the budget).
                if len(req.prompt) > budget and prefills:
                    break
                # +1 headroom token so the first decode step after
                # prefill cannot immediately preempt someone.
                if not self.cache.allocate(req.seq_id,
                                           len(req.prompt) + 1):
                    parked = True
                    break
                self.waiting.popleft()
            req.status = RUNNING
            budget -= len(req.prompt)
            prefills.append(req)
            self.running.append(req)
            self.num_admitted += 1
        if parked:
            self.park_events += 1
        return prefills, decodes

    def _preempt(self, req: Request) -> None:
        """Recompute-style eviction: drop the sequence's blocks and send
        it back to the FRONT of the waitqueue. Already-emitted tokens
        were already streamed; on re-admission the prompt is extended
        with them so the recompute continues where it left off."""
        self.cache.free(req.seq_id)
        req.prompt = req.prompt + req.out_tokens
        req.max_new_tokens -= len(req.out_tokens)
        req.out_tokens = []
        req.status = WAITING
        req.preemptions += 1
        self.num_preempted += 1
        self.running = [r for r in self.running if r is not req]
        with self._lock:
            self.waiting.appendleft(req)

    # -------------------------------------------------------------- release
    def release(self, req: Request, status: str,
                error: Optional[BaseException] = None) -> int:
        """Terminal transition: mark + free blocks IMMEDIATELY. Safe to
        call for any state; returns blocks freed."""
        req.status = status
        req.error = error
        self.running = [r for r in self.running if r is not req]
        return self.cache.free(req.seq_id)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            waiting = len(self.waiting)
        return {
            "waiting": waiting,
            "running": len(self.running),
            "max_num_seqs": self.max_num_seqs,
            "prefill_token_budget": self.prefill_token_budget,
            "max_queued_requests": self.max_queued_requests,
            "num_admitted": self.num_admitted,
            "num_preempted": self.num_preempted,
            "park_events": self.park_events,
        }
