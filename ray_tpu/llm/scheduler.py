"""Iteration-level (continuous) batching scheduler with chunked prefill
and prefix-cache-aware admission (reference role: Orca's iteration-level
scheduling + vLLM's scheduler/policy — admission from a bounded
waitqueue each step, prefill and decode composed per iteration,
eviction-by-recompute on KV OOM, chunked prefill so one long prompt
cannot stall the running batch).

Per engine iteration ``schedule()`` returns the work for ONE step:

- ``chunks``: ``(request, start, length)`` prefill slices, composed
  under the prefill token budget. A prompt longer than the budget runs
  as several chunks across ITERATIONS — between any two of its chunks
  every running sequence decodes one token, so the batch's inter-token
  stall is bounded by one chunk's compute, never one prompt's
  (``max_prefill_tokens_per_step`` pins that bound). Admission
  allocates the prompt's blocks via ``PagedKVCache.allocate_prefix``:
  leading blocks already cached are SHARED and their tokens never
  appear in any chunk (the prefix-cache fast path). A request that
  doesn't fit PARKS at the head of the queue and is retried every
  iteration (KV-full never crashes, it waits for blocks to free).
- ``decodes``: every fully-prefilled running sequence, each guaranteed
  a writable physical slot for its next token. When the pool is empty
  mid-decode the YOUNGEST running sequence is preempted (block refs
  dropped, request requeued for recompute — vLLM's recompute eviction
  policy; on re-admission its still-cached prefix blocks match again),
  so the oldest work always completes.

Finished/cancelled sequences release their block references immediately
via ``release()`` — a short request parked behind a long one resumes on
the very next iteration, and only refcount-0 blocks actually free.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time as _time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.exceptions import RequestSheddedError
from ray_tpu.llm.kv_cache import PagedKVCache

__all__ = ["EngineQueueFull", "Request", "Scheduler",
           "WAITING", "RUNNING", "FINISHED", "CANCELLED", "FAILED", "SHED"]

WAITING = "WAITING"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
CANCELLED = "CANCELLED"
FAILED = "FAILED"
SHED = "SHED"  # evicted pre-admission by the load-shedding policy

_seq_counter = itertools.count(1)


class EngineQueueFull(RequestSheddedError, RuntimeError):
    """The bounded admission waitqueue is at capacity and the incoming
    request did not outrank anything waiting (backpressure — callers
    should retry/shed; the engine never buffers unboundedly). A
    ``RequestSheddedError``: overload is policy, not failure."""


class Request:
    """One sequence moving through the engine."""

    def __init__(self, prompt: List[int], max_new_tokens: int,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0,
                 seed: Optional[int] = None,
                 priority: int = 0):
        if not prompt:
            raise ValueError("empty prompt")
        self.seq_id = next(_seq_counter)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.seed = seed
        # Admission class: 0 = most important. Under overload the
        # waitqueue admits better classes first and sheds worse ones.
        self.priority = int(priority)
        self.out_tokens: List[int] = []
        # TTFT decomposition stamps (monotonic; wall_submit anchors
        # span timestamps): queue wait = t_sched - t_submit, prefill =
        # t_prefill_done - t_sched, decode = t_finish - t_prefill_done.
        self.t_submit = _time.monotonic()
        self.wall_submit = _time.time()
        self.t_sched: Optional[float] = None
        self.t_prefill_done: Optional[float] = None
        # Disagg adoption stamp: when the sequence's prompt KV was
        # pulled p2p and grafted (transfer phase = t_transfer_done -
        # t_prefill_done); None for colocated requests.
        self.t_transfer_done: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_finish: Optional[float] = None
        self.trace = None  # tracing wire context ((trace_id, span_id))
        # Disagg prefill pool: keep KV blocks allocated after the last
        # prefill token (for p2p export) instead of freeing on finish.
        self.hold_after_prefill = False
        # (blocks, bytes) shipped for this sequence — llm.kv_ship span.
        self.kv_ship: Optional[Tuple[int, int]] = None
        # Prompt tokens whose KV is in the cache (prefix-cache hits at
        # admission + chunks computed so far). The request decodes only
        # once this reaches len(prompt).
        self.prefill_pos = 0
        self.cached_prompt_tokens = 0  # prefix-cache hits (observability)
        self.status = WAITING
        self.error: Optional[BaseException] = None
        self.preemptions = 0
        # Token stream to the consumer: ints, then one (sentinel, payload).
        self.output_queue: "queue.SimpleQueue" = queue.SimpleQueue()

    # Next position to be computed/written in the KV cache.
    @property
    def num_tokens(self) -> int:
        return len(self.prompt) + len(self.out_tokens)

    @property
    def last_token(self) -> int:
        return self.out_tokens[-1] if self.out_tokens else self.prompt[-1]

    @property
    def prefilling(self) -> bool:
        return self.prefill_pos < len(self.prompt)

    def finished(self) -> bool:
        return self.status in (FINISHED, CANCELLED, FAILED, SHED)


class Scheduler:
    """Waitqueue + running set over one PagedKVCache. NOT thread-safe on
    its own — the engine serializes all calls under its step lock."""

    def __init__(self, cache: PagedKVCache, *, max_num_seqs: int = 8,
                 prefill_token_budget: int = 2048,
                 max_queued_requests: int = 64):
        self.cache = cache
        self.max_num_seqs = int(max_num_seqs)
        self.prefill_token_budget = int(prefill_token_budget)
        self.max_queued_requests = int(max_queued_requests)
        self.waiting: "deque[Request]" = deque()
        self.running: List[Request] = []
        self._lock = threading.Lock()  # waitqueue only (submit vs step)
        # -- counters --
        self.num_admitted = 0
        self.num_preempted = 0
        self.park_events = 0  # iterations where KV-full parked admission
        self.prefill_chunks_scheduled = 0
        self.max_prefill_tokens_per_step = 0  # chunked-prefill stall bound
        self.coscheduled_steps = 0  # iterations with BOTH chunks + decodes
        # Load-shedding accounting ("shed-by-policy", distinct from
        # failures): requests refused or evicted pre-admission when the
        # bounded waitqueue overflowed, per priority class.
        self.shed_requests = 0
        self.shed_by_class: Dict[int, int] = {}
        self.submitted_by_class: Dict[int, int] = {}

    # ------------------------------------------------------------ admission
    def submit(self, req: Request) -> Optional[Request]:
        """Enqueue ``req`` in (priority, FIFO) order. At capacity the
        LOWEST-priority waiting request loses: if something waiting is
        strictly worse than the newcomer it is evicted and returned (the
        caller fails it with a typed ``RequestSheddedError``); otherwise
        the newcomer itself is shed by raising ``EngineQueueFull``.
        Overload therefore degrades by policy — the best classes keep
        their queue slots — instead of by arrival order."""
        with self._lock:
            self.submitted_by_class[req.priority] = \
                self.submitted_by_class.get(req.priority, 0) + 1
            victim: Optional[Request] = None
            if len(self.waiting) >= self.max_queued_requests:
                # Eviction candidates: requests that were never admitted
                # (preemptions == 0). A recompute-preempted request is
                # mid-generation — its consumer already holds streamed
                # tokens — so shedding it would break the "shed happens
                # pre-admission, retry is safe" contract.
                candidates = [w for w in self.waiting
                              if w.preemptions == 0]
                worst = max(
                    candidates,
                    key=lambda w: (w.priority, w.seq_id), default=None)
                if worst is None or worst.priority <= req.priority:
                    self.shed_requests += 1
                    self.shed_by_class[req.priority] = \
                        self.shed_by_class.get(req.priority, 0) + 1
                    raise EngineQueueFull(
                        f"waitqueue at capacity "
                        f"({self.max_queued_requests} requests) and no "
                        f"waiting request has lower priority than "
                        f"class {req.priority}",
                        priority=req.priority)
                self.waiting.remove(worst)
                self.shed_requests += 1
                self.shed_by_class[worst.priority] = \
                    self.shed_by_class.get(worst.priority, 0) + 1
                victim = worst
            # Stable priority insert: behind every waiting request of an
            # equal-or-better class (FIFO within a class).
            idx = len(self.waiting)
            for i, w in enumerate(self.waiting):
                if w.priority > req.priority:
                    idx = i
                    break
            self.waiting.insert(idx, req)
            return victim

    def remove_waiting(self, req: Request) -> bool:
        with self._lock:
            try:
                self.waiting.remove(req)
                return True
            except ValueError:
                return False

    def queue_depth(self) -> int:
        with self._lock:
            return len(self.waiting)

    # ------------------------------------------------------------- schedule
    def schedule(self) -> Tuple[List[Tuple[Request, int, int]],
                                List[Request]]:
        """Compose one iteration: (prefill chunks, decode batch). Every
        returned request has cache slots for the tokens this step will
        write."""
        self.running = [r for r in self.running if not r.finished()]

        # 1) Guarantee a writable slot for each fully-prefilled running
        #    sequence's next token; evict-on-OOM: preempt the youngest
        #    until the rest fit. (Mid-prefill sequences already own every
        #    block their prompt needs — allocated at admission — so only
        #    decode growth can run the pool dry.)
        decodes: List[Request] = []
        i = 0
        while i < len(self.running):
            req = self.running[i]
            if req.prefilling:
                i += 1
                continue
            if self.cache.ensure_slot(req.seq_id, req.num_tokens):
                decodes.append(req)
                i += 1
                continue
            victim = self.running[-1]
            if victim is req and len(self.running) == 1:
                # A single sequence that outgrew the whole pool cannot
                # make progress by eviction; fail it loudly.
                raise MemoryError(
                    f"sequence {req.seq_id} needs more KV blocks than "
                    f"the pool holds ({self.cache.usable_blocks})")
            self._preempt(victim)
            decodes = [r for r in decodes if r is not victim]
            # retry the same index (running list shrank behind it)

        # 2) Continue chunked prefills of already-running sequences
        #    (admission order) under the per-iteration token budget.
        chunks: List[Tuple[Request, int, int]] = []
        budget = self.prefill_token_budget
        for req in self.running:
            if budget <= 0:
                break
            if req.prefilling:
                n = min(len(req.prompt) - req.prefill_pos, budget)
                chunks.append((req, req.prefill_pos, n))
                budget -= n

        # 3) Admit from the waitqueue under the remaining budget / seq
        #    cap / pool headroom. Stop at the first request that doesn't
        #    fit: FIFO order is the fairness contract (no head-of-line
        #    skip). Admission allocates the FULL prompt's blocks (+1
        #    headroom token so the first decode step after prefill
        #    cannot immediately preempt someone), sharing every cached
        #    prefix block; only the unshared tail enters the chunk plan.
        parked = False
        while budget > 0:
            with self._lock:
                if not self.waiting:
                    break
                req = self.waiting[0]
                if len(self.running) >= self.max_num_seqs:
                    break
                cached = self.cache.allocate_prefix(
                    req.seq_id, req.prompt, extra_tokens=1)
                if cached is None:
                    parked = True
                    break
                self.waiting.popleft()
            req.status = RUNNING
            req.prefill_pos = cached
            req.cached_prompt_tokens = cached
            n = min(len(req.prompt) - cached, budget)
            chunks.append((req, cached, n))
            budget -= n
            if req.t_sched is None:
                req.t_sched = _time.monotonic()  # queue-wait boundary
            self.running.append(req)
            self.num_admitted += 1
        if parked:
            self.park_events += 1
        if chunks:
            self.prefill_chunks_scheduled += len(chunks)
            step_tokens = sum(n for _, _, n in chunks)
            self.max_prefill_tokens_per_step = max(
                self.max_prefill_tokens_per_step, step_tokens)
            if decodes:
                self.coscheduled_steps += 1
        return chunks, decodes

    def _preempt(self, req: Request) -> None:
        """Recompute-style eviction: drop the sequence's block refs and
        send it back to the FRONT of the waitqueue. Already-emitted
        tokens were already streamed; on re-admission the prompt is
        extended with them so the recompute continues where it left off
        (and its still-registered prefix blocks match again — a
        preempted sequence usually re-prefills only what the cache
        lost)."""
        self.cache.free(req.seq_id)
        req.prompt = req.prompt + req.out_tokens
        req.max_new_tokens -= len(req.out_tokens)
        req.out_tokens = []
        req.prefill_pos = 0
        req.status = WAITING
        req.preemptions += 1
        self.num_preempted += 1
        self.running = [r for r in self.running if r is not req]
        with self._lock:
            self.waiting.appendleft(req)

    def adopt_running(self, req: Request) -> None:
        """Join an externally-prefilled (disagg-adopted) sequence to the
        running set: its prompt KV was grafted from a prefill replica
        and its first token already streamed, so it enters directly at
        the decode phase. May transiently push the running set one past
        ``max_num_seqs``; admission (which checks the cap) simply
        pauses until a slot frees."""
        req.status = RUNNING
        self.running.append(req)
        self.num_admitted += 1

    # -------------------------------------------------------------- release
    def release(self, req: Request, status: str,
                error: Optional[BaseException] = None,
                free_blocks: bool = True) -> int:
        """Terminal transition: mark + drop block refs IMMEDIATELY (only
        refcount-0 blocks actually free — shared prefix blocks stay with
        their other holders). Safe to call for any state; returns blocks
        freed. ``free_blocks=False`` keeps the block table alive past
        the terminal transition — the disagg prefill pool's hold-for-
        export path, balanced by ``InferenceEngine.release_held``."""
        req.status = status
        req.error = error
        self.running = [r for r in self.running if r is not req]
        if not free_blocks:
            return 0
        return self.cache.free(req.seq_id)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            waiting = len(self.waiting)
        return {
            "waiting": waiting,
            "running": len(self.running),
            "max_num_seqs": self.max_num_seqs,
            "prefill_token_budget": self.prefill_token_budget,
            "max_queued_requests": self.max_queued_requests,
            "num_admitted": self.num_admitted,
            "num_preempted": self.num_preempted,
            "park_events": self.park_events,
            "prefill_chunks_scheduled": self.prefill_chunks_scheduled,
            "max_prefill_tokens_per_step": self.max_prefill_tokens_per_step,
            "coscheduled_steps": self.coscheduled_steps,
            "shed_requests": self.shed_requests,
            "shed_by_class": dict(self.shed_by_class),
            "submitted_by_class": dict(self.submitted_by_class),
        }
