"""Paged KV cache with copy-on-write shared prefix blocks: fixed-size
blocks in preallocated device arrays plus host-side block-table,
refcount, and content-hash bookkeeping (reference role: vLLM's
BlockSpaceManager + automatic prefix caching over PagedAttention —
Kwon et al.).

The device side is two arrays ``[L, num_blocks, block_size, n_kv_heads,
head_dim]`` built once by ``models.init_kv_cache`` (the HBM pool; under
tensor parallelism the ``n_kv_heads`` axis is sharded across the mesh).
The host side is pure integer bookkeeping: a free list, per-sequence
block tables, and — new in this tier — a **prefix cache**:

- Every FULL block of a sequence's prompt is content-hashed by its
  *parent-chain digest*: ``digest_i = H(digest_{i-1}, tokens_i)``, so a
  digest match guarantees the entire token prefix up to and including
  that block is identical. Partial tail blocks are never shared.
- ``allocate_prefix`` matches a new prompt's leading full blocks
  against registered digests and SHARES the hits (refcount++), so the
  engine skips recomputing those prefill tokens entirely
  (``prefill_tokens_saved``). At most ``len(prompt) - 1`` tokens are
  ever skipped — the last prompt position must be computed for logits —
  and a fully-cached prompt therefore writes into its final shared
  block, which **copies on write** first (``cow_copies``).
- Freeing a sequence decrements refcounts; only blocks that hit
  refcount 0 become reusable. Registered zero-ref blocks PARK in an LRU
  *cached-free* tier instead of the plain free list: they still serve
  prefix hits, and are reclaimed (digest entries removed — a later
  admit can never resurrect a reclaimed block) only when the free list
  runs dry.

Block 0 is the NULL block: it is never handed out, and every padded
block-table entry (and padded batch row) points at it, so the jitted
prefill/decode programs can scatter unconditionally — garbage writes
land in block 0 and the attention mask keeps them out of every softmax.

Accounting counters (``blocks_in_use``, peaks, totals, prefix hit/save
counters) are the observable contract the engine tests pin: a
mid-generation ``close()`` of a sequence sharing prefix blocks must
free only its private blocks.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["KVCacheOOM", "PagedKVCache", "chain_digests"]

NULL_BLOCK = 0

# Truncated hex digest length. 16 hex chars = 64 bits per chained link —
# collisions are negligible at any realistic cache size, and compact
# digests keep the router's replica prefix reports small on the wire.
_DIGEST_LEN = 16


def chain_digests(tokens: Sequence[int], block_size: int) -> List[str]:
    """Parent-chained content digests of every FULL block of ``tokens``.

    ``out[i]`` commits to ``tokens[: (i+1)*block_size]`` — the whole
    prefix, not just block ``i`` — so matching ``out[i]`` against a
    registered block implies every earlier block matched too. Shared by
    the cache (registration/matching) and the Serve prefix router
    (scoring replicas by cached-prefix overlap).
    """
    out: List[str] = []
    parent = b""
    for i in range(len(tokens) // block_size):
        blk = tokens[i * block_size:(i + 1) * block_size]
        h = hashlib.blake2b(digest_size=16)
        h.update(parent)
        h.update(np.asarray(blk, np.int64).tobytes())
        parent = h.digest()
        out.append(h.hexdigest()[:_DIGEST_LEN])
    return out


class KVCacheOOM(RuntimeError):
    """No free blocks for a required allocation (after eviction)."""


class PagedKVCache:
    """Host-side block manager for one preallocated paged KV pool."""

    def __init__(self, model_cfg, num_blocks: int, block_size: int,
                 dtype=None, *, enable_prefix_caching: bool = True,
                 mesh=None, rules=None):
        if num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is NULL)")
        from ray_tpu.models import init_kv_cache

        self.model_cfg = model_cfg
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.enable_prefix_caching = bool(enable_prefix_caching)
        self.mesh = mesh
        self.data = init_kv_cache(model_cfg, num_blocks, block_size, dtype)
        if mesh is not None:
            # TP decode: the pool lives sharded along n_kv_heads across
            # the mesh; every block id indexes the same logical block on
            # every shard, so the host bookkeeping below is unchanged.
            import jax

            from ray_tpu.parallel.sharding import kv_cache_specs

            specs = kv_cache_specs(rules)
            self.data = {
                k: jax.device_put(
                    v, jax.sharding.NamedSharding(mesh, specs[k]))
                for k, v in self.data.items()
            }
        # LIFO free list, block 0 reserved as NULL.
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._tables: Dict[int, List[int]] = {}
        self._ref: Dict[int, int] = {}           # block -> refcount
        self._block_key: Dict[int, str] = {}     # block -> chain digest
        self._key_block: Dict[str, int] = {}     # chain digest -> block
        # refcount-0 registered blocks, LRU order (oldest first).
        self._cached_free: "OrderedDict[int, str]" = OrderedDict()
        # per-sequence prompt digests + how many blocks are registered.
        self._prompt_digests: Dict[int, List[str]] = {}
        self._registered_upto: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._block_copy = None  # lazily-jitted COW block copy
        # Aux pools (e.g. the spec-decode DRAFT model's KV) ride the
        # SAME block tables/refcounts: one host-side manager, N device
        # pools. Every lifecycle event that moves bytes (COW copy,
        # export, graft) covers every pool, so a sequence's draft cache
        # can never diverge from its flagship cache's block layout.
        self._aux: Dict[str, Dict[str, object]] = {}
        # -- accounting (engine tests/bench read these) --
        self.peak_blocks_in_use = 0
        self.total_blocks_allocated = 0
        self.total_blocks_freed = 0
        # -- prefix-cache counters --
        self.prefix_cache_queries = 0      # allocate_prefix calls
        self.prefix_cache_hits = 0         # queries with >= 1 cached token
        self.prefix_cache_query_tokens = 0  # prompt tokens seen by queries
        self.prefill_tokens_saved = 0      # tokens skipped via cache hits
        self.cow_copies = 0                # shared blocks copied on write
        self.cached_blocks_evicted = 0     # cached-free blocks reclaimed
        # -- disagg p2p shipping counters --
        self.blocks_exported = 0           # blocks packed for p2p publish
        self.blocks_grafted = 0            # p2p blocks scattered back in

    # ------------------------------------------------------------- capacity
    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1  # NULL block excluded

    @property
    def blocks_in_use(self) -> int:
        """Blocks referenced by live sequences (cached-free blocks are
        reusable on demand, so they count as free)."""
        return self.usable_blocks - self.free_blocks

    @property
    def free_blocks(self) -> int:
        return len(self._free) + len(self._cached_free)

    @property
    def cached_free_blocks(self) -> int:
        return len(self._cached_free)

    def blocks_for_tokens(self, n_tokens: int) -> int:
        return -(-max(n_tokens, 1) // self.block_size)

    def can_allocate(self, n_tokens: int) -> bool:
        return self.blocks_for_tokens(n_tokens) <= self.free_blocks

    # ----------------------------------------------------- internal helpers
    def _pop_block(self) -> Optional[int]:
        """One reusable block: plain free list first, else reclaim the
        LRU cached-free block (its digest entries are removed FIRST, so
        a racing admit can never match — and resurrect — a block whose
        bytes are about to be overwritten)."""
        if self._free:
            return self._free.pop()
        if self._cached_free:
            block, key = self._cached_free.popitem(last=False)
            self._deregister(block)
            self.cached_blocks_evicted += 1
            return block
        return None

    def _deregister(self, block: int) -> None:
        key = self._block_key.pop(block, None)
        if key is not None and self._key_block.get(key) == block:
            del self._key_block[key]

    def _release_block(self, block: int) -> int:
        """Drop one reference; returns 1 when the block became free."""
        n = self._ref.get(block, 1) - 1
        if n > 0:
            self._ref[block] = n
            return 0
        self._ref.pop(block, None)
        key = self._block_key.get(block)
        if key is not None and self.enable_prefix_caching:
            self._cached_free[block] = key
            self._cached_free.move_to_end(block)
        else:
            self._deregister(block)
            self._free.append(block)
        self.total_blocks_freed += 1
        return 1

    def _activate_cached(self, block: int) -> None:
        """A prefix hit on a cached-free block pulls it back live."""
        self._cached_free.pop(block, None)

    def _note_alloc(self, n: int) -> None:
        self.total_blocks_allocated += n
        self.peak_blocks_in_use = max(self.peak_blocks_in_use,
                                      self.blocks_in_use)

    # ----------------------------------------------------------- allocation
    def allocate(self, seq_id: int, n_tokens: int) -> bool:
        """Give ``seq_id`` a fresh (non-prefix-matched) table covering
        ``n_tokens`` positions. Returns False (allocating nothing) when
        the pool can't cover it — the scheduler parks the request."""
        need = self.blocks_for_tokens(n_tokens)
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id} already allocated")
            if need > self.free_blocks:
                return False
            blocks = [self._pop_block() for _ in range(need)]
            for b in blocks:
                self._ref[b] = 1
            self._tables[seq_id] = blocks
            self._note_alloc(need)
            return True

    def allocate_prefix(self, seq_id: int, prompt: Sequence[int],
                        extra_tokens: int = 1) -> Optional[int]:
        """Allocate ``seq_id``'s table for ``len(prompt) + extra_tokens``
        positions, SHARING every leading full block whose chain digest
        is already cached. Returns the number of prompt tokens whose KV
        is already present (the engine skips prefilling them), or None
        when the pool can't cover the unshared remainder.

        At most ``len(prompt) - 1`` tokens are reported cached (the last
        prompt position must be computed for its logits); when the match
        extends into the written range — a fully-cached prompt — the
        boundary shared block is copied on write here, so the prefill
        scatter never touches a block another sequence references.
        """
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        need = self.blocks_for_tokens(len(prompt) + extra_tokens)
        if not self.enable_prefix_caching:
            ok = self.allocate(seq_id, len(prompt) + extra_tokens)
            return 0 if ok else None
        digests = chain_digests(prompt, self.block_size)
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id} already allocated")
            self.prefix_cache_queries += 1
            self.prefix_cache_query_tokens += len(prompt)
            matched: List[int] = []
            for d in digests:
                b = self._key_block.get(d)
                if b is None:
                    break
                matched.append(b)
            cached_len = min(len(matched) * self.block_size,
                             len(prompt) - 1)
            # A fully-cached prompt writes into its final matched block:
            # if that block has a LIVE holder the write will copy-on-
            # write, costing one extra block — reserve it up front so a
            # request that fits never parks on a failed COW pop.
            cow_blocks = 0
            if matched and cached_len < len(matched) * self.block_size:
                boundary = matched[cached_len // self.block_size]
                if self._ref.get(boundary, 0) >= 1:
                    cow_blocks = 1
            if need - len(matched) + cow_blocks > self.free_blocks - sum(
                    1 for b in matched if b in self._cached_free):
                # The fresh remainder doesn't fit even after reclaiming
                # every NON-matched cached-free block. (Matched blocks
                # sitting in cached-free must not be double-counted as
                # reclaimable — activating them below removes them from
                # that tier.)
                return None
            # Take the shared prefix: refcount++ (activating any block
            # parked in cached-free), then fresh blocks for the rest.
            for b in matched:
                self._activate_cached(b)
                self._ref[b] = self._ref.get(b, 0) + 1
            def _rollback(fresh):
                for f in fresh:
                    self._ref.pop(f, None)
                    self._free.append(f)
                for m in matched:
                    if self._release_block(m):
                        self.total_blocks_freed -= 1  # not a real free

            fresh: List[int] = []
            for _ in range(need - len(matched)):
                b = self._pop_block()
                if b is None:  # raced: roll everything back
                    _rollback(fresh)
                    return None
                self._ref[b] = 1
                fresh.append(b)
            table = matched + fresh
            # Fully-cached boundary: the prefill will write positions
            # [cached_len, ...) and cached_len falls INSIDE the last
            # matched block -> copy-on-write it now.
            if matched and cached_len < len(matched) * self.block_size:
                idx = cached_len // self.block_size
                try:
                    table[idx] = self._make_private(table[idx])
                except KVCacheOOM:
                    _rollback(fresh)
                    return None
            self._tables[seq_id] = table
            self._prompt_digests[seq_id] = digests
            self._registered_upto[seq_id] = 0
            self._note_alloc(need - len(matched))
            if cached_len > 0:
                self.prefix_cache_hits += 1
                self.prefill_tokens_saved += cached_len
            return cached_len

    def _make_private(self, block: int) -> int:
        """Return a privately-owned, unregistered block with ``block``'s
        content: the block itself if this sequence is the only holder
        (deregistered — its content is about to change), else a fresh
        copy-on-write clone."""
        if self._ref.get(block, 1) <= 1:
            self._deregister(block)
            return block
        new = self._pop_block()
        if new is None:
            raise KVCacheOOM("no free block for copy-on-write")
        self._copy_block_data(block, new)
        self._ref[block] -= 1
        self._ref[new] = 1
        self._note_alloc(1)  # COW is a real allocation: keep the
        self.cow_copies += 1  # allocated/freed/peak contract balanced
        return new

    def _copy_block_data(self, src: int, dst: int) -> None:
        """Device-side block copy (K and V, all layers). Jitted with the
        pool donated so XLA updates the arrays IN PLACE on accelerators
        — an eager ``.at[].set`` would materialize a second full pool
        (2x HBM transient + full-pool copy) for a one-block COW. Block
        ids ride as traced scalars, so every COW hits one compiled
        program."""
        if self._block_copy is None:
            import jax

            donate = (0,) if jax.default_backend() != "cpu" else ()
            self._block_copy = jax.jit(
                lambda arr, s, d: arr.at[:, d].set(arr[:, s]),
                donate_argnums=donate)
        import jax.numpy as jnp

        s = jnp.int32(src)
        d = jnp.int32(dst)
        # Aux pools (draft KV) share the block layout, so a COW must
        # copy EVERY pool — a draft cache left pointing at the donor
        # block would silently read another sequence's context.
        for pool in (self.data, *self._aux.values()):
            for name in ("k", "v"):
                pool[name] = self._block_copy(pool[name], s, d)

    def ensure_slot(self, seq_id: int, position: int) -> bool:
        """Grow ``seq_id``'s table so ``position`` has a physical slot
        this sequence may WRITE (at most one new block per decode step;
        a shared or registered block containing the slot goes private
        first). False on pool-empty — the scheduler's eviction policy
        decides who pays."""
        with self._lock:
            table = self._tables[seq_id]
            need_len = position // self.block_size + 1
            if need_len <= len(table):
                idx = position // self.block_size
                b = table[idx]
                if self._ref.get(b, 1) > 1 or b in self._block_key:
                    try:
                        table[idx] = self._make_private(b)
                    except KVCacheOOM:
                        return False
                return True
            b = self._pop_block()
            if b is None:
                return False
            self._ref[b] = 1
            table.append(b)
            self._note_alloc(1)
            return True

    def free(self, seq_id: int) -> int:
        """Release ``seq_id``'s references. Returns the number of blocks
        that actually became free (shared blocks stay with their other
        holders; registered ones park in the cached-free tier)."""
        with self._lock:
            blocks = self._tables.pop(seq_id, None)
            self._prompt_digests.pop(seq_id, None)
            self._registered_upto.pop(seq_id, None)
            if not blocks:
                return 0
            return sum(self._release_block(b) for b in reversed(blocks))

    # ------------------------------------------------- aux pools + shipping
    def attach_aux(self, name: str, model_cfg, dtype=None) -> None:
        """Attach a second device pool (same ``num_blocks`` ×
        ``block_size`` geometry, possibly a different model config —
        the spec-decode DRAFT cache) that rides this manager's block
        tables. Aux pools are copied on COW, packed by
        ``export_blocks`` and scattered by ``graft_blocks``."""
        if self.mesh is not None:
            raise ValueError("aux pools are not supported under tensor "
                             "parallelism")
        from ray_tpu.models import init_kv_cache

        with self._lock:
            if name in self._aux:
                raise ValueError(f"aux pool {name!r} already attached")
            self._aux[name] = init_kv_cache(
                model_cfg, self.num_blocks, self.block_size, dtype)

    def aux_data(self, name: str):
        return self._aux[name]

    def set_aux_data(self, name: str, data) -> None:
        self._aux[name] = data

    def export_blocks(self, seq_id: int, start_block: int = 0) -> dict:
        """Pack ``seq_id``'s block data from ``start_block`` on into a
        host-side payload (per-layer block ranges for every pool) —
        what a disagg prefill replica publishes as an owner-resolved
        p2p object. ``start_block`` implements tail-only shipping: a
        decode replica whose prefix cache already holds the leading
        blocks asks only for the unshared remainder.

        Device arrays are immutable values, so the gather runs outside
        the lock against a snapshot reference — a concurrent step's
        functional cache update cannot corrupt the export."""
        with self._lock:
            table = list(self._tables[seq_id])
            data = self.data
            aux = {n: dict(p) for n, p in self._aux.items()}
        blocks = table[start_block:]
        payload = {
            "start_block": int(start_block),
            "blocks": len(blocks),
            "block_size": self.block_size,
        }
        if blocks:
            import jax.numpy as jnp

            idx = jnp.asarray(np.asarray(blocks, np.int32))
            payload["k"] = np.asarray(data["k"][:, idx])
            payload["v"] = np.asarray(data["v"][:, idx])
            payload["aux"] = {
                n: {"k": np.asarray(p["k"][:, idx]),
                    "v": np.asarray(p["v"][:, idx])}
                for n, p in aux.items()
            }
        with self._lock:
            self.blocks_exported += len(blocks)
        return payload

    def graft_blocks(self, seq_id: int, payload: dict,
                     start_block: Optional[int] = None) -> int:
        """Scatter a peer's exported block payload into ``seq_id``'s
        table, starting at ``start_block`` (default: the payload's own
        start). A graft start past the payload's start skips leading
        payload blocks — the decode replica's prefix cache covered more
        than the shipping plan assumed, and shared blocks must NEVER be
        written. Every target block is asserted privately owned and
        unregistered. Returns blocks grafted.

        Callers serialize against the engine step loop (the engine
        grafts under its step lock): the scatter is a read-modify-write
        of the pool arrays and must not interleave with a step's own
        functional update."""
        if int(payload["block_size"]) != self.block_size:
            raise ValueError(
                f"payload block_size {payload['block_size']} != pool "
                f"block_size {self.block_size}")
        src_start = int(payload["start_block"])
        n = int(payload["blocks"])
        sb = src_start if start_block is None else int(start_block)
        off = sb - src_start
        if off < 0:
            raise ValueError(
                f"graft start {sb} precedes payload start {src_start}")
        with self._lock:
            table = self._tables[seq_id]
            dst = table[sb:src_start + n]
            if not dst:
                return 0
            for b in dst:
                if self._ref.get(b, 0) != 1 or b in self._block_key:
                    raise ValueError(
                        f"graft target block {b} is shared or "
                        f"registered — grafting would corrupt another "
                        f"sequence's context")
            import jax.numpy as jnp

            idx = jnp.asarray(np.asarray(dst, np.int32))
            sl = slice(off, off + len(dst))
            for pool, part in [(self.data, payload)] + [
                    (self._aux[a], p)
                    for a, p in payload.get("aux", {}).items()
                    if a in self._aux]:
                for name in ("k", "v"):
                    arr = jnp.asarray(part[name][:, sl],
                                      pool[name].dtype)
                    pool[name] = pool[name].at[:, idx].set(arr)
            self.blocks_grafted += len(dst)
            return len(dst)

    # -------------------------------------------------------- prefix cache
    def register_prefix(self, seq_id: int, upto_tokens: int) -> int:
        """Register ``seq_id``'s full prompt blocks covering
        ``[0, upto_tokens)`` as shareable (called by the engine after
        each prefill chunk lands, so a concurrent same-prefix request
        can hit blocks mid-prefill). Returns blocks newly registered."""
        if not self.enable_prefix_caching:
            return 0
        with self._lock:
            digests = self._prompt_digests.get(seq_id)
            if digests is None:
                return 0
            table = self._tables.get(seq_id, [])
            start = self._registered_upto.get(seq_id, 0)
            upto = min(upto_tokens // self.block_size, len(digests),
                       len(table))
            new = 0
            for i in range(start, upto):
                d = digests[i]
                b = table[i]
                if d in self._key_block or b in self._block_key:
                    continue  # another block is already canonical
                self._key_block[d] = b
                self._block_key[b] = d
                new += 1
            self._registered_upto[seq_id] = max(start, upto)
            return new

    def prefix_digest(self, limit: Optional[int] = None) -> List[str]:
        """Report of every registered chain digest (live and cached-
        free) — what a Serve replica publishes so the router can score
        it by cached-prefix overlap. Unbounded by default (at most
        ``usable_blocks`` entries); with ``limit``, the FIRST-registered
        digests are kept — registration runs prefix-to-tail, so a
        truncated report degrades long chains' tails, never their
        heads, and the router's leading-overlap scoring stays sound."""
        with self._lock:
            out = list(self._key_block.keys())
        return out if limit is None else out[:limit]

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._ref.get(block, 0)

    # -------------------------------------------------------------- queries
    def table(self, seq_id: int) -> List[int]:
        with self._lock:
            return list(self._tables[seq_id])

    def num_seqs(self) -> int:
        with self._lock:
            return len(self._tables)

    def padded_tables(self, seq_ids: List[int],
                      pad_len: Optional[int] = None) -> np.ndarray:
        """[B, M] int32 block-table batch, rows padded with NULL_BLOCK."""
        with self._lock:
            tables = [self._tables[s] for s in seq_ids]
        m = max((len(t) for t in tables), default=1)
        m = max(m, pad_len or 1)
        out = np.full((len(tables), m), NULL_BLOCK, np.int32)
        for i, t in enumerate(tables):
            out[i, :len(t)] = t
        return out

    def stats(self) -> Dict[str, int]:
        with self._lock:
            saved = self.prefill_tokens_saved
            seen = self.prefix_cache_query_tokens
            return {
                "num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "usable_blocks": self.usable_blocks,
                "blocks_in_use": self.blocks_in_use,
                "free_blocks": self.free_blocks,
                "cached_free_blocks": len(self._cached_free),
                "peak_blocks_in_use": self.peak_blocks_in_use,
                "total_blocks_allocated": self.total_blocks_allocated,
                "total_blocks_freed": self.total_blocks_freed,
                "live_sequences": len(self._tables),
                "prefix_caching_enabled": int(self.enable_prefix_caching),
                "prefix_cache_queries": self.prefix_cache_queries,
                "prefix_cache_hits": self.prefix_cache_hits,
                "prefill_tokens_saved": saved,
                "prefix_cache_hit_rate": (saved / seen) if seen else 0.0,
                "cow_copies": self.cow_copies,
                "cached_blocks_evicted": self.cached_blocks_evicted,
                "blocks_exported": self.blocks_exported,
                "blocks_grafted": self.blocks_grafted,
                "aux_pools": list(self._aux),
            }
