"""Paged KV cache: fixed-size blocks in preallocated device arrays plus
the host-side block-table bookkeeping (reference role: vLLM's
BlockSpaceManager over PagedAttention — Kwon et al.).

The device side is two arrays ``[L, num_blocks, block_size, n_kv_heads,
head_dim]`` built once by ``models.init_kv_cache`` (the HBM pool). The
host side is pure integer bookkeeping: a free list and per-sequence
block tables. Admission, growth, and release move block IDS, never
bytes — freeing a finished sequence is O(blocks) list appends, and its
blocks are immediately reusable by any parked request.

Block 0 is the NULL block: it is never handed out, and every padded
block-table entry (and padded batch row) points at it, so the jitted
prefill/decode programs can scatter unconditionally — garbage writes
land in block 0 and the attention mask keeps them out of every softmax.

Accounting counters (``blocks_in_use``, peaks, totals) are the
observable contract the engine tests pin: a mid-generation ``close()``
must return the sequence's blocks to the free list immediately.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

__all__ = ["KVCacheOOM", "PagedKVCache"]

NULL_BLOCK = 0


class KVCacheOOM(RuntimeError):
    """No free blocks for a required allocation (after eviction)."""


class PagedKVCache:
    """Host-side block manager for one preallocated paged KV pool."""

    def __init__(self, model_cfg, num_blocks: int, block_size: int,
                 dtype=None):
        if num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is NULL)")
        from ray_tpu.models import init_kv_cache

        self.model_cfg = model_cfg
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.data = init_kv_cache(model_cfg, num_blocks, block_size, dtype)
        # LIFO free list, block 0 reserved as NULL.
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._tables: Dict[int, List[int]] = {}
        self._lock = threading.Lock()
        # -- accounting (engine tests/bench read these) --
        self.peak_blocks_in_use = 0
        self.total_blocks_allocated = 0
        self.total_blocks_freed = 0

    # ------------------------------------------------------------- capacity
    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1  # NULL block excluded

    @property
    def blocks_in_use(self) -> int:
        return self.usable_blocks - len(self._free)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def blocks_for_tokens(self, n_tokens: int) -> int:
        return -(-max(n_tokens, 1) // self.block_size)

    def can_allocate(self, n_tokens: int) -> bool:
        return self.blocks_for_tokens(n_tokens) <= len(self._free)

    # ----------------------------------------------------------- allocation
    def allocate(self, seq_id: int, n_tokens: int) -> bool:
        """Give ``seq_id`` a fresh table covering ``n_tokens`` positions.
        Returns False (allocating nothing) when the pool can't cover it —
        the scheduler parks the request instead of crashing."""
        need = self.blocks_for_tokens(n_tokens)
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id} already allocated")
            if need > len(self._free):
                return False
            blocks = [self._free.pop() for _ in range(need)]
            self._tables[seq_id] = blocks
            self.total_blocks_allocated += need
            self.peak_blocks_in_use = max(self.peak_blocks_in_use,
                                          self.blocks_in_use)
            return True

    def ensure_slot(self, seq_id: int, position: int) -> bool:
        """Grow ``seq_id``'s table so ``position`` has a physical slot
        (at most one new block per decode step). False on pool-empty —
        the scheduler's eviction policy decides who pays."""
        with self._lock:
            table = self._tables[seq_id]
            need_len = position // self.block_size + 1
            if need_len <= len(table):
                return True
            if not self._free:
                return False
            table.append(self._free.pop())
            self.total_blocks_allocated += 1
            self.peak_blocks_in_use = max(self.peak_blocks_in_use,
                                          self.blocks_in_use)
            return True

    def free(self, seq_id: int) -> int:
        """Release every block of ``seq_id`` back to the free list.
        Returns the number of blocks freed (0 if unknown/already freed)."""
        with self._lock:
            blocks = self._tables.pop(seq_id, None)
            if not blocks:
                return 0
            self._free.extend(reversed(blocks))
            self.total_blocks_freed += len(blocks)
            return len(blocks)

    # -------------------------------------------------------------- queries
    def table(self, seq_id: int) -> List[int]:
        with self._lock:
            return list(self._tables[seq_id])

    def num_seqs(self) -> int:
        with self._lock:
            return len(self._tables)

    def padded_tables(self, seq_ids: List[int],
                      pad_len: Optional[int] = None) -> np.ndarray:
        """[B, M] int32 block-table batch, rows padded with NULL_BLOCK."""
        with self._lock:
            tables = [self._tables[s] for s in seq_ids]
        m = max((len(t) for t in tables), default=1)
        m = max(m, pad_len or 1)
        out = np.full((len(tables), m), NULL_BLOCK, np.int32)
        for i, t in enumerate(tables):
            out[i, :len(t)] = t
        return out

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "usable_blocks": self.usable_blocks,
                "blocks_in_use": self.blocks_in_use,
                "free_blocks": len(self._free),
                "peak_blocks_in_use": self.peak_blocks_in_use,
                "total_blocks_allocated": self.total_blocks_allocated,
                "total_blocks_freed": self.total_blocks_freed,
                "live_sequences": len(self._tables),
            }
