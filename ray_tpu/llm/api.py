"""Serve-facing LLM API (reference role: Ray Serve LLM's
``build_openai_app``/LLMServer — a deployment builder that wraps the
inference engine in a streaming Serve deployment).

``build_llm_app(EngineConfig(...))`` returns a Serve Application whose
replicas each own one ``InferenceEngine``. Requests stream: the replica
handler is a generator, so ``handle.options(stream=True)`` (and the
HTTP proxy's ``?stream=1`` chunked path) deliver each token as the
engine's iteration commits it, with first-token latency of one prefill.
Closing the stream client-side cancels the replica generator between
yields (the streaming task plane's contract), which unwinds into the
engine as ``GeneratorExit`` and frees the sequence's KV blocks
immediately.

Autoscaling: an open token stream counts as one ongoing request on its
replica until exhausted or closed (serve router accounting), so a
deployment built with ``autoscaling_config=`` scales up under
streaming-heavy load; ``queue_depth()`` additionally exposes the
engine's parked-admission depth per replica for dashboards/policies.

Prefix-aware routing: every replica exposes ``prefix_digest()`` — the
chain digests of its cached KV blocks. The Serve controller polls it
off the request path, and the router scores replicas by cached-prefix
overlap with each request's prompt, so same-system-prompt traffic
lands where its prefill is already cached (load-slack bounded; see
``serve/router.py``).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional, Union

from ray_tpu.llm.engine import EngineConfig, InferenceEngine

__all__ = ["build_llm_app", "LLMServer"]


class LLMServer:
    """Replica class: one engine, streaming ``__call__``.

    A request is either a token list (``[1, 2, 3]``) or a dict
    ``{"prompt": [...], "max_new_tokens": n, "temperature": t,
    "eos_token_id": e, "seed": s, "priority": p}``. Yields one int
    token id per generated token. ``priority`` (0 = most important,
    default) feeds the engine's load-shedding admission: under
    overload the bounded waitqueue evicts the worst class with a typed
    ``RequestSheddedError`` instead of timing everyone out.
    """

    # Declarative marker for serve handles: deployments of this class
    # consume LLM request dicts, so a traced handle reshapes the
    # request payload to carry its context (no class-identity probing
    # or llm imports in the serve layer).
    _consumes_llm_requests = True

    def __init__(self, engine_config: Optional[EngineConfig] = None,
                 params: Optional[dict] = None,
                 warm_prefix: Optional[list] = None):
        import time as _time

        from ray_tpu._private import tracing

        self.init_started_monotonic = _time.monotonic()
        self.first_token_monotonic: Optional[float] = None
        self.warmed_prefix_tokens = 0
        # Cold-start chain: a replica constructed because a traced
        # request forced a scale-up parents its init span to the
        # launch context the environment carried here.
        init_span = tracing.begin(
            "replica.init", parent=tracing.cold_start_parent(),
            component="replica") if tracing.active() else None
        try:
            self.engine = InferenceEngine(engine_config, params=params)
            if warm_prefix:
                # Prefix-cache warming (cold-start attack): prefill the
                # shared prompt ONCE at replica start, so it registers
                # as COW shared blocks before the first request — the
                # first same-prefix request computes only its unique
                # tail, and the controller's next prefix_digest poll
                # advertises the warmed chain to the router (requests
                # route here WITH a cache hit from token one).
                tokens = [int(t) for t in warm_prefix]
                for _ in self.engine.generate(tokens, max_new_tokens=1):
                    pass
                self.warmed_prefix_tokens = len(tokens)
        except BaseException:
            # Close the span AND restore the thread-local ambient
            # context — this worker thread is reused, and a dangling
            # replica.init context would silently adopt every later
            # span on it.
            tracing.finish(init_span, status="error")
            raise
        self.ready_monotonic = _time.monotonic()
        tracing.finish(init_span,
                       warmed_prefix_tokens=self.warmed_prefix_tokens)

    def __call__(self, request: Union[Dict[str, Any], list]
                 ) -> Iterator[int]:
        if isinstance(request, dict):
            prompt = request["prompt"]
            kwargs = {k: request[k] for k in
                      ("max_new_tokens", "eos_token_id", "temperature",
                       "seed", "priority") if k in request}
            if request.get("_trace") is not None:
                # Trace context rode the serve request dict: the
                # engine stamps queue/prefill/decode spans under it.
                kwargs["trace"] = request["_trace"]
        else:
            prompt, kwargs = request, {}
        # A cancelled stream raises GeneratorExit through here; the
        # engine generator's finally-cancel frees the KV blocks.
        for tok in self.engine.generate([int(t) for t in prompt],
                                        **kwargs):
            if self.first_token_monotonic is None:
                # Cold-start SLO anchor: the first REAL token this
                # replica served, on the machine-shared monotonic
                # clock — pairs with the autoscaler's launch_started.
                import time as _time

                self.first_token_monotonic = _time.monotonic()
            yield tok

    # ------------------------------------------------- replica telemetry
    def queue_depth(self) -> int:
        return self.engine.queue_depth()

    def autoscale_metric(self, name: str) -> float:
        """Custom autoscaling signal by name (the controller polls this
        when ``AutoscalingConfig.metric`` names one): ``queue_depth`` —
        prompts parked behind compute; ``kv_blocks_in_use`` — resident
        sequences' cache footprint. Unknown names read 0.0 (a
        misconfigured metric holds the pool steady instead of
        flapping it)."""
        if name == "queue_depth":
            return float(self.engine.queue_depth())
        if name == "kv_blocks_in_use":
            return float(self.engine.cache.stats()["blocks_in_use"])
        return 0.0

    def stats(self) -> Dict[str, Any]:
        out = dict(self.engine.stats())
        out.update({
            "init_started_monotonic": self.init_started_monotonic,
            "ready_monotonic": self.ready_monotonic,
            "first_token_monotonic": self.first_token_monotonic,
            "warmed_prefix_tokens": self.warmed_prefix_tokens,
        })
        return out

    def prefix_digest(self) -> Dict[str, Any]:
        """Compact cached-prefix report: the chain digests of every
        registered KV block on this replica (plus the block size they
        chain over). The Serve controller polls this off the request
        path and the router scores replicas by cached-prefix overlap —
        a same-system-prompt request lands where its prefill is already
        cached."""
        digests = self.engine.cache.prefix_digest()
        cap = 8192  # bound the wire payload; truncation is REPORTED
        return {
            "block_size": self.engine.cache.block_size,
            "digests": digests[:cap],
            "truncated": max(0, len(digests) - cap),
        }


def build_llm_app(engine_config: Optional[EngineConfig] = None, *,
                  name: str = "llm", num_replicas: int = 1,
                  autoscaling_config: Optional[dict] = None,
                  max_ongoing_requests: Optional[int] = None,
                  params: Optional[dict] = None,
                  warm_prefix: Optional[list] = None,
                  ray_actor_options: Optional[dict] = None):
    """Build a Serve Application serving ``engine_config``.

    Every replica constructs its own engine; with ``params=None`` the
    weights init from ``engine_config.param_seed`` in-replica, so all
    replicas serve identical weights without shipping arrays through
    the deployment args. Deploy with ``serve.run(app)`` and stream via
    ``handle.options(stream=True).remote({...})`` or
    ``POST /<name>?stream=1``.

    ``max_ongoing_requests`` bounds total in-flight requests across the
    deployment (priority admission: lower classes shed first with a
    typed ``RequestSheddedError`` / HTTP 503 + Retry-After); request
    ``priority`` rides the request dict.

    ``warm_prefix`` (token list — typically the shared system prompt)
    is prefilled by every NEW replica at construction, so an
    autoscaled-up or scale-to-zero-woken replica serves its first
    same-prefix request with the prefill already cached (cold-start
    SLO attack; ``stats()['warmed_prefix_tokens']`` confirms it).
    """
    from ray_tpu import serve

    dep = serve.deployment(
        LLMServer, name=name, num_replicas=num_replicas,
        autoscaling_config=autoscaling_config,
        max_ongoing_requests=max_ongoing_requests,
        ray_actor_options=ray_actor_options)
    return dep.bind(engine_config, params, warm_prefix)
