// Dependency-tracking ready queue — the scheduler hot loop in native code.
//
// Reference role: the raylet's LocalTaskManager/ClusterTaskManager dispatch
// queues (src/ray/raylet/scheduling/*.cc [unverified]). Re-designed for the
// wave model this framework uses: a task graph with in-degrees, a ready
// ring, and O(1) completion propagation over a CSR edge list — the host-side
// companion of the on-device lax.while_loop frontier executor (host side
// feeds waves; device side runs them).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <pthread.h>

namespace {

struct TaskQueue {
  uint32_t max_tasks;
  uint32_t max_edges;
  int32_t* indeg;        // per task
  uint8_t* done;
  // CSR edges: head[t]..head[t+1] gives consumer list.
  uint32_t* edge_src;    // staging before seal
  uint32_t* edge_dst;
  uint32_t num_edges;
  uint32_t* csr_head;    // size max_tasks+1
  uint32_t* csr_dst;
  int sealed;
  // Ready ring.
  uint32_t* ring;
  uint32_t ring_cap;
  uint32_t rhead, rtail;
  uint32_t num_tasks;
  uint32_t num_done;
  pthread_mutex_t mu;
  pthread_cond_t cv;
};

void push_ready(TaskQueue* q, uint32_t t) {
  q->ring[q->rtail % q->ring_cap] = t;
  q->rtail++;
}

timespec deadline_from_ms(int64_t timeout_ms) {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) { ts.tv_sec++; ts.tv_nsec -= 1000000000L; }
  return ts;
}

}  // namespace

extern "C" {

void* rtn_tq_create(uint32_t max_tasks, uint32_t max_edges) {
  TaskQueue* q = new TaskQueue();
  memset(q, 0, sizeof(TaskQueue));
  q->max_tasks = max_tasks;
  q->max_edges = max_edges;
  q->indeg = new int32_t[max_tasks]();
  q->done = new uint8_t[max_tasks]();
  q->edge_src = new uint32_t[max_edges];
  q->edge_dst = new uint32_t[max_edges];
  q->csr_head = new uint32_t[max_tasks + 1]();
  q->csr_dst = new uint32_t[max_edges];
  q->ring_cap = max_tasks + 1;
  q->ring = new uint32_t[q->ring_cap];
  pthread_mutex_init(&q->mu, nullptr);
  pthread_cond_init(&q->cv, nullptr);
  return q;
}

void rtn_tq_destroy(void* handle) {
  TaskQueue* q = (TaskQueue*)handle;
  delete[] q->indeg;
  delete[] q->done;
  delete[] q->edge_src;
  delete[] q->edge_dst;
  delete[] q->csr_head;
  delete[] q->csr_dst;
  delete[] q->ring;
  pthread_mutex_destroy(&q->mu);
  pthread_cond_destroy(&q->cv);
  delete q;
}

int rtn_tq_add_task(void* handle, uint32_t task_id) {
  TaskQueue* q = (TaskQueue*)handle;
  if (task_id >= q->max_tasks || q->sealed) return -1;
  if (task_id + 1 > q->num_tasks) q->num_tasks = task_id + 1;
  return 0;
}

int rtn_tq_add_edge(void* handle, uint32_t src, uint32_t dst) {
  TaskQueue* q = (TaskQueue*)handle;
  if (q->sealed || q->num_edges >= q->max_edges) return -1;
  if (src >= q->max_tasks || dst >= q->max_tasks) return -1;
  q->edge_src[q->num_edges] = src;
  q->edge_dst[q->num_edges] = dst;
  q->num_edges++;
  q->indeg[dst]++;
  return 0;
}

int rtn_tq_seal(void* handle) {
  TaskQueue* q = (TaskQueue*)handle;
  if (q->sealed) return -1;
  // Build CSR: counting sort by src.
  for (uint32_t i = 0; i < q->num_edges; i++) q->csr_head[q->edge_src[i] + 1]++;
  for (uint32_t t = 0; t < q->num_tasks; t++) q->csr_head[t + 1] += q->csr_head[t];
  uint32_t* cursor = new uint32_t[q->num_tasks]();
  for (uint32_t i = 0; i < q->num_edges; i++) {
    uint32_t s = q->edge_src[i];
    q->csr_dst[q->csr_head[s] + cursor[s]] = q->edge_dst[i];
    cursor[s]++;
  }
  delete[] cursor;
  pthread_mutex_lock(&q->mu);
  q->sealed = 1;
  for (uint32_t t = 0; t < q->num_tasks; t++)
    if (q->indeg[t] == 0) push_ready(q, t);
  pthread_cond_broadcast(&q->cv);
  pthread_mutex_unlock(&q->mu);
  return 0;
}

// Mark tasks complete; newly-ready consumers enter the ring. Batched — the
// wave executor completes a whole wave per call.
int rtn_tq_complete(void* handle, const uint32_t* tasks, uint32_t n) {
  TaskQueue* q = (TaskQueue*)handle;
  pthread_mutex_lock(&q->mu);
  for (uint32_t i = 0; i < n; i++) {
    uint32_t t = tasks[i];
    if (t >= q->num_tasks || q->done[t]) continue;
    q->done[t] = 1;
    q->num_done++;
    for (uint32_t e = q->csr_head[t]; e < q->csr_head[t + 1]; e++) {
      uint32_t c = q->csr_dst[e];
      if (--q->indeg[c] == 0) push_ready(q, c);
    }
  }
  pthread_cond_broadcast(&q->cv);
  pthread_mutex_unlock(&q->mu);
  return 0;
}

// Pop up to max ready tasks (the next wave). Blocks up to timeout_ms when
// none ready and the graph is unfinished; returns count (0 = all done or
// timeout).
int rtn_tq_pop_wave(void* handle, uint32_t* out, uint32_t max,
                    int64_t timeout_ms) {
  TaskQueue* q = (TaskQueue*)handle;
  timespec dl = deadline_from_ms(timeout_ms);
  pthread_mutex_lock(&q->mu);
  while (q->rhead == q->rtail && q->num_done < q->num_tasks) {
    if (pthread_cond_timedwait(&q->cv, &q->mu, &dl) == ETIMEDOUT) {
      pthread_mutex_unlock(&q->mu);
      return 0;
    }
  }
  uint32_t n = 0;
  while (q->rhead != q->rtail && n < max) {
    out[n++] = q->ring[q->rhead % q->ring_cap];
    q->rhead++;
  }
  pthread_mutex_unlock(&q->mu);
  return (int)n;
}

uint32_t rtn_tq_num_done(void* handle) {
  TaskQueue* q = (TaskQueue*)handle;
  pthread_mutex_lock(&q->mu);
  uint32_t d = q->num_done;
  pthread_mutex_unlock(&q->mu);
  return d;
}

uint32_t rtn_tq_num_tasks(void* handle) {
  return ((TaskQueue*)handle)->num_tasks;
}

}  // extern "C"
