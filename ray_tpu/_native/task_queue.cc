// Dependency-tracking ready queue — the scheduler hot loop in native code.
//
// Reference role: the raylet's LocalTaskManager/ClusterTaskManager dispatch
// queues (src/ray/raylet/scheduling/*.cc [unverified]). Re-designed for the
// wave model this framework uses: a task graph with in-degrees, a ready
// ring, and O(1) completion propagation over a CSR edge list — the host-side
// companion of the on-device lax.while_loop frontier executor (host side
// feeds waves; device side runs them).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <pthread.h>

namespace {

struct TaskQueue {
  uint32_t max_tasks;
  uint32_t max_edges;
  int32_t* indeg;        // per task
  uint8_t* done;
  // CSR edges: head[t]..head[t+1] gives consumer list.
  uint32_t* edge_src;    // staging before seal
  uint32_t* edge_dst;
  uint32_t num_edges;
  uint32_t* csr_head;    // size max_tasks+1
  uint32_t* csr_dst;
  int sealed;
  // Ready ring.
  uint32_t* ring;
  uint32_t ring_cap;
  uint32_t rhead, rtail;
  uint32_t num_tasks;
  uint32_t num_done;
  pthread_mutex_t mu;
  pthread_cond_t cv;
};

void push_ready(TaskQueue* q, uint32_t t) {
  q->ring[q->rtail % q->ring_cap] = t;
  q->rtail++;
}

timespec deadline_from_ms(int64_t timeout_ms) {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) { ts.tv_sec++; ts.tv_nsec -= 1000000000L; }
  return ts;
}

}  // namespace

extern "C" {

void* rtn_tq_create(uint32_t max_tasks, uint32_t max_edges) {
  TaskQueue* q = new TaskQueue();
  memset(q, 0, sizeof(TaskQueue));
  q->max_tasks = max_tasks;
  q->max_edges = max_edges;
  q->indeg = new int32_t[max_tasks]();
  q->done = new uint8_t[max_tasks]();
  q->edge_src = new uint32_t[max_edges];
  q->edge_dst = new uint32_t[max_edges];
  q->csr_head = new uint32_t[max_tasks + 1]();
  q->csr_dst = new uint32_t[max_edges];
  q->ring_cap = max_tasks + 1;
  q->ring = new uint32_t[q->ring_cap];
  pthread_mutex_init(&q->mu, nullptr);
  pthread_cond_init(&q->cv, nullptr);
  return q;
}

void rtn_tq_destroy(void* handle) {
  TaskQueue* q = (TaskQueue*)handle;
  delete[] q->indeg;
  delete[] q->done;
  delete[] q->edge_src;
  delete[] q->edge_dst;
  delete[] q->csr_head;
  delete[] q->csr_dst;
  delete[] q->ring;
  pthread_mutex_destroy(&q->mu);
  pthread_cond_destroy(&q->cv);
  delete q;
}

int rtn_tq_add_task(void* handle, uint32_t task_id) {
  TaskQueue* q = (TaskQueue*)handle;
  if (task_id >= q->max_tasks || q->sealed) return -1;
  if (task_id + 1 > q->num_tasks) q->num_tasks = task_id + 1;
  return 0;
}

int rtn_tq_add_edge(void* handle, uint32_t src, uint32_t dst) {
  TaskQueue* q = (TaskQueue*)handle;
  if (q->sealed || q->num_edges >= q->max_edges) return -1;
  if (src >= q->max_tasks || dst >= q->max_tasks) return -1;
  q->edge_src[q->num_edges] = src;
  q->edge_dst[q->num_edges] = dst;
  q->num_edges++;
  q->indeg[dst]++;
  return 0;
}

int rtn_tq_seal(void* handle) {
  TaskQueue* q = (TaskQueue*)handle;
  if (q->sealed) return -1;
  // Build CSR: counting sort by src.
  for (uint32_t i = 0; i < q->num_edges; i++) q->csr_head[q->edge_src[i] + 1]++;
  for (uint32_t t = 0; t < q->num_tasks; t++) q->csr_head[t + 1] += q->csr_head[t];
  uint32_t* cursor = new uint32_t[q->num_tasks]();
  for (uint32_t i = 0; i < q->num_edges; i++) {
    uint32_t s = q->edge_src[i];
    q->csr_dst[q->csr_head[s] + cursor[s]] = q->edge_dst[i];
    cursor[s]++;
  }
  delete[] cursor;
  pthread_mutex_lock(&q->mu);
  q->sealed = 1;
  for (uint32_t t = 0; t < q->num_tasks; t++)
    if (q->indeg[t] == 0) push_ready(q, t);
  pthread_cond_broadcast(&q->cv);
  pthread_mutex_unlock(&q->mu);
  return 0;
}

// Mark tasks complete; newly-ready consumers enter the ring. Batched — the
// wave executor completes a whole wave per call.
int rtn_tq_complete(void* handle, const uint32_t* tasks, uint32_t n) {
  TaskQueue* q = (TaskQueue*)handle;
  pthread_mutex_lock(&q->mu);
  for (uint32_t i = 0; i < n; i++) {
    uint32_t t = tasks[i];
    if (t >= q->num_tasks || q->done[t]) continue;
    q->done[t] = 1;
    q->num_done++;
    for (uint32_t e = q->csr_head[t]; e < q->csr_head[t + 1]; e++) {
      uint32_t c = q->csr_dst[e];
      if (--q->indeg[c] == 0) push_ready(q, c);
    }
  }
  pthread_cond_broadcast(&q->cv);
  pthread_mutex_unlock(&q->mu);
  return 0;
}

// Pop up to max ready tasks (the next wave). Blocks up to timeout_ms when
// none ready and the graph is unfinished; returns count (0 = all done or
// timeout).
int rtn_tq_pop_wave(void* handle, uint32_t* out, uint32_t max,
                    int64_t timeout_ms) {
  TaskQueue* q = (TaskQueue*)handle;
  timespec dl = deadline_from_ms(timeout_ms);
  pthread_mutex_lock(&q->mu);
  while (q->rhead == q->rtail && q->num_done < q->num_tasks) {
    if (pthread_cond_timedwait(&q->cv, &q->mu, &dl) == ETIMEDOUT) {
      pthread_mutex_unlock(&q->mu);
      return 0;
    }
  }
  uint32_t n = 0;
  while (q->rhead != q->rtail && n < max) {
    out[n++] = q->ring[q->rhead % q->ring_cap];
    q->rhead++;
  }
  pthread_mutex_unlock(&q->mu);
  return (int)n;
}

uint32_t rtn_tq_num_done(void* handle) {
  TaskQueue* q = (TaskQueue*)handle;
  pthread_mutex_lock(&q->mu);
  uint32_t d = q->num_done;
  pthread_mutex_unlock(&q->mu);
  return d;
}

uint32_t rtn_tq_num_tasks(void* handle) {
  return ((TaskQueue*)handle)->num_tasks;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Dynamic dependency queue: incremental task adds (no seal), generation-
// tagged 64-bit handles so slots recycle safely. This is the live scheduler
// hot loop — the LocalScheduler feeds every submitted task through it when
// the native layer is available (reference role: LocalTaskManager's
// waiting/ready queues + DependencyManager counts, src/ray/raylet/
// local_task_manager.cc [unverified]).
//
// Handle layout: (generation << 32) | slot. A dep edge may only be added
// while the consumer is uncommitted; completion walks the producer's
// consumer list, decrements in-degrees, and frees the slot (gen++), so a
// stale handle can never alias a recycled slot.
// ---------------------------------------------------------------------------

namespace {

constexpr uint32_t kNil = 0xffffffffu;

struct DynQueue {
  uint32_t cap, edge_cap;
  int32_t* indeg;       // per slot
  uint32_t* gen;        // per slot generation
  uint8_t* state;       // 0=free, 1=allocated (deps still arriving), 2=committed
  uint32_t* head;       // per slot: first outgoing edge (consumers)
  uint32_t* enext;      // per edge
  uint32_t* edst;       // per edge: consumer slot
  uint32_t* egen;       // per edge: consumer generation at add time (edges
                        // into an aborted slot go stale instead of
                        // corrupting whatever recycled the slot)
  uint32_t* edge_free;  // stack
  uint32_t edge_free_top;
  uint32_t* slot_free;  // stack
  uint32_t slot_free_top;
  uint64_t* ring;       // ready handles
  uint32_t ring_cap, rhead, rtail;
  uint64_t num_pending, num_done;
  pthread_mutex_t mu;
  pthread_cond_t cv;
};

inline uint64_t dq_handle(DynQueue* q, uint32_t slot) {
  return ((uint64_t)q->gen[slot] << 32) | slot;
}

// Validates a handle; returns slot or kNil.
inline uint32_t dq_slot(DynQueue* q, uint64_t h) {
  uint32_t s = (uint32_t)h;
  if (s >= q->cap || q->state[s] == 0) return kNil;
  if (q->gen[s] != (uint32_t)(h >> 32)) return kNil;
  return s;
}

}  // namespace

extern "C" {

void* rtn_dq_create(uint32_t cap, uint32_t edge_cap) {
  DynQueue* q = new DynQueue();
  memset(q, 0, sizeof(DynQueue));
  q->cap = cap;
  q->edge_cap = edge_cap;
  q->indeg = new int32_t[cap]();
  q->gen = new uint32_t[cap]();
  q->state = new uint8_t[cap]();
  q->head = new uint32_t[cap];
  q->enext = new uint32_t[edge_cap];
  q->edst = new uint32_t[edge_cap];
  q->egen = new uint32_t[edge_cap];
  q->edge_free = new uint32_t[edge_cap];
  for (uint32_t i = 0; i < edge_cap; i++) q->edge_free[i] = edge_cap - 1 - i;
  q->edge_free_top = edge_cap;
  q->slot_free = new uint32_t[cap];
  for (uint32_t i = 0; i < cap; i++) q->slot_free[i] = cap - 1 - i;
  q->slot_free_top = cap;
  q->ring_cap = cap + 1;
  q->ring = new uint64_t[q->ring_cap];
  pthread_mutex_init(&q->mu, nullptr);
  pthread_cond_init(&q->cv, nullptr);
  return q;
}

void rtn_dq_destroy(void* handle) {
  DynQueue* q = (DynQueue*)handle;
  delete[] q->indeg;
  delete[] q->gen;
  delete[] q->state;
  delete[] q->head;
  delete[] q->enext;
  delete[] q->edst;
  delete[] q->egen;
  delete[] q->edge_free;
  delete[] q->slot_free;
  delete[] q->ring;
  pthread_mutex_destroy(&q->mu);
  pthread_cond_destroy(&q->cv);
  delete q;
}

// Allocate a task slot; returns handle, or 0 when full (0 is never a valid
// handle because gen starts at 1 for slot 0 on first reuse... guard: we
// bump gen at alloc so gen >= 1 always).
uint64_t rtn_dq_alloc(void* handle) {
  DynQueue* q = (DynQueue*)handle;
  pthread_mutex_lock(&q->mu);
  if (q->slot_free_top == 0) {
    pthread_mutex_unlock(&q->mu);
    return 0;
  }
  uint32_t s = q->slot_free[--q->slot_free_top];
  q->gen[s]++;            // gen >= 1: handle 0 stays invalid
  q->state[s] = 1;
  q->indeg[s] = 0;
  q->head[s] = kNil;
  q->num_pending++;
  uint64_t h = dq_handle(q, s);
  pthread_mutex_unlock(&q->mu);
  return h;
}

// Record consumer <- producer dependency. No-op (0) when the producer has
// already completed (stale handle). -1: bad consumer; -3: edge table full.
int rtn_dq_add_dep(void* handle, uint64_t task, uint64_t dep) {
  DynQueue* q = (DynQueue*)handle;
  pthread_mutex_lock(&q->mu);
  uint32_t t = dq_slot(q, task);
  if (t == kNil || q->state[t] != 1) {
    pthread_mutex_unlock(&q->mu);
    return -1;
  }
  uint32_t d = dq_slot(q, dep);
  if (d == kNil) {  // producer already done — dependency satisfied
    pthread_mutex_unlock(&q->mu);
    return 0;
  }
  if (q->edge_free_top == 0) {
    pthread_mutex_unlock(&q->mu);
    return -3;
  }
  uint32_t e = q->edge_free[--q->edge_free_top];
  q->edst[e] = t;
  q->egen[e] = q->gen[t];
  q->enext[e] = q->head[d];
  q->head[d] = e;
  q->indeg[t]++;
  pthread_mutex_unlock(&q->mu);
  return 0;
}

// All deps recorded: task becomes eligible; rings immediately if indeg==0.
int rtn_dq_commit(void* handle, uint64_t task) {
  DynQueue* q = (DynQueue*)handle;
  pthread_mutex_lock(&q->mu);
  uint32_t t = dq_slot(q, task);
  if (t == kNil || q->state[t] != 1) {
    pthread_mutex_unlock(&q->mu);
    return -1;
  }
  q->state[t] = 2;
  if (q->indeg[t] == 0) {
    q->ring[q->rtail] = dq_handle(q, t);
    if (++q->rtail == q->ring_cap) q->rtail = 0;
    pthread_cond_broadcast(&q->cv);
  }
  pthread_mutex_unlock(&q->mu);
  return 0;
}

// Task finished (outputs stored): ready its consumers, free the slot.
int rtn_dq_complete(void* handle, uint64_t task) {
  DynQueue* q = (DynQueue*)handle;
  pthread_mutex_lock(&q->mu);
  uint32_t t = dq_slot(q, task);
  if (t == kNil || q->state[t] != 2) {
    pthread_mutex_unlock(&q->mu);
    return -1;
  }
  uint32_t e = q->head[t];
  int woke = 0;
  while (e != kNil) {
    uint32_t c = q->edst[e];
    if (q->gen[c] == q->egen[e] &&
        --q->indeg[c] == 0 && q->state[c] == 2) {
      q->ring[q->rtail] = dq_handle(q, c);
      if (++q->rtail == q->ring_cap) q->rtail = 0;
      woke = 1;
    }
    uint32_t nxt = q->enext[e];
    q->edge_free[q->edge_free_top++] = e;
    e = nxt;
  }
  q->state[t] = 0;
  q->gen[t]++;  // invalidate stale handles
  q->slot_free[q->slot_free_top++] = t;
  q->num_pending--;
  q->num_done++;
  if (woke) pthread_cond_broadcast(&q->cv);
  pthread_mutex_unlock(&q->mu);
  return 0;
}

// Abandon an allocated-or-committed task that never ran (e.g. the caller
// hit the edge-table-full MemoryError mid-registration and is unwinding).
// Consumers' edges are released as satisfied; edges INTO this slot from
// still-pending producers go stale via the generation tag and are freed
// when those producers complete. Not counted in num_done.
int rtn_dq_abort(void* handle, uint64_t task) {
  DynQueue* q = (DynQueue*)handle;
  pthread_mutex_lock(&q->mu);
  uint32_t t = dq_slot(q, task);
  if (t == kNil) {
    pthread_mutex_unlock(&q->mu);
    return -1;
  }
  uint32_t e = q->head[t];
  int woke = 0;
  while (e != kNil) {
    uint32_t c = q->edst[e];
    if (q->gen[c] == q->egen[e] &&
        --q->indeg[c] == 0 && q->state[c] == 2) {
      q->ring[q->rtail] = dq_handle(q, c);
      if (++q->rtail == q->ring_cap) q->rtail = 0;
      woke = 1;
    }
    uint32_t nxt = q->enext[e];
    q->edge_free[q->edge_free_top++] = e;
    e = nxt;
  }
  q->state[t] = 0;
  q->gen[t]++;
  q->slot_free[q->slot_free_top++] = t;
  q->num_pending--;
  if (woke) pthread_cond_broadcast(&q->cv);
  pthread_mutex_unlock(&q->mu);
  return 0;
}

// Pop up to max ready handles; blocks up to timeout_ms when none ready.
int rtn_dq_pop(void* handle, uint64_t* out, uint32_t max, int64_t timeout_ms) {
  DynQueue* q = (DynQueue*)handle;
  timespec dl = deadline_from_ms(timeout_ms);
  pthread_mutex_lock(&q->mu);
  while (q->rhead == q->rtail) {
    if (pthread_cond_timedwait(&q->cv, &q->mu, &dl) == ETIMEDOUT) {
      pthread_mutex_unlock(&q->mu);
      return 0;
    }
  }
  uint32_t n = 0;
  while (q->rhead != q->rtail && n < max) {
    out[n++] = q->ring[q->rhead];
    if (++q->rhead == q->ring_cap) q->rhead = 0;
  }
  pthread_mutex_unlock(&q->mu);
  return (int)n;
}

// Wake any pop_wave blocked in cv (shutdown path).
void rtn_dq_wake(void* handle) {
  DynQueue* q = (DynQueue*)handle;
  pthread_mutex_lock(&q->mu);
  pthread_cond_broadcast(&q->cv);
  pthread_mutex_unlock(&q->mu);
}

uint64_t rtn_dq_num_pending(void* handle) {
  DynQueue* q = (DynQueue*)handle;
  pthread_mutex_lock(&q->mu);
  uint64_t v = q->num_pending;
  pthread_mutex_unlock(&q->mu);
  return v;
}

uint64_t rtn_dq_num_done(void* handle) {
  DynQueue* q = (DynQueue*)handle;
  pthread_mutex_lock(&q->mu);
  uint64_t v = q->num_done;
  pthread_mutex_unlock(&q->mu);
  return v;
}

}  // extern "C"
