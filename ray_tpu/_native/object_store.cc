// Shared-memory object store with mutable objects.
//
// Reference role: src/ray/object_manager/plasma/ (store/create/seal/get over
// shm) + the mutable-object support used by compiled-graph channels
// (experimental_mutable_object_provider.cc) [unverified]. Re-designed, not
// ported: one POSIX shm arena per "node", a fixed open-addressing object
// table and bump allocator inside the segment (all offsets, no pointers),
// process-shared pthread mutex/cond per mutable slot for the single-writer/
// multi-reader versioned-buffer protocol. The host-side channel substrate;
// device payloads stay in HBM and only control/small objects cross here.
//
// Build: g++ -O2 -shared -fPIC -pthread object_store.cc task_queue.cc
//        -o libray_tpu_native.so -lrt

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52415954505553ULL;  // "RAYTPUS"

enum EntryState : uint32_t {
  kEmpty = 0,
  kCreated = 1,   // allocated, not sealed
  kSealed = 2,    // immutable, readable
  kMutable = 3,   // versioned mutable object
  kTombstone = 4, // deleted
};

struct MutableCtrl {
  pthread_mutex_t mu;
  pthread_cond_t cv;
  uint64_t version;        // incremented per committed write
  uint32_t num_readers;
  uint32_t reads_remaining; // readers yet to consume current version
  uint32_t closed;
  uint32_t pad;
  uint64_t payload_size;    // size of current version's payload
};

struct Entry {
  uint64_t id;        // 0 = empty
  uint32_t state;
  uint32_t pad;
  uint64_t offset;    // payload offset in arena
  uint64_t capacity;  // allocated bytes
  uint64_t size;      // sealed payload size
  uint64_t ctrl_offset;  // MutableCtrl offset (mutable objects)
};

// Reclaimed arena blocks (delete/destroy) for reuse: a best-fit free list
// with neighbor coalescing and end-of-arena giveback — the plasma-role
// answer to long-running stores, where a pure bump allocator would leak
// every staged argument and return payload forever.
struct FreeBlock {
  uint64_t offset;
  uint64_t size;  // aligned bytes
};

constexpr uint32_t kMaxFreeBlocks = 2048;

struct Header {
  uint64_t magic;
  uint64_t arena_size;
  uint64_t alloc_cursor;     // bump allocator cursor
  uint32_t max_objects;
  uint32_t free_count;       // live entries in the free list
  uint64_t used_objects;
  uint64_t free_bytes;       // total bytes parked in the free list
  pthread_mutex_t table_mu;  // protects table + allocator + free list
  // Free list, then entry table, then payload heap follow.
};

struct Store {
  Header* hdr;
  FreeBlock* freelist;
  Entry* table;
  uint8_t* base;
  uint64_t mapped_size;
  char name[256];
  int owner;
};

uint64_t align8(uint64_t v) { return (v + 7) & ~7ULL; }

Entry* find_slot(Store* s, uint64_t id, bool for_insert) {
  uint32_t n = s->hdr->max_objects;
  uint64_t h = id * 0x9E3779B97F4A7C15ULL;
  Entry* first_tomb = nullptr;
  for (uint32_t i = 0; i < n; i++) {
    Entry* e = &s->table[(h + i) % n];
    if (e->id == id && e->state != kEmpty && e->state != kTombstone)
      return e;
    if (e->state == kTombstone && for_insert && !first_tomb) first_tomb = e;
    if (e->state == kEmpty) return for_insert ? (first_tomb ? first_tomb : e)
                                              : nullptr;
  }
  return for_insert ? first_tomb : nullptr;
}

uint64_t arena_alloc(Store* s, uint64_t size) {
  // Caller holds table_mu. Best-fit from the free list, else bump; 0 on
  // exhaustion.
  Header* h = s->hdr;
  uint64_t need = align8(size);
  uint32_t best = UINT32_MAX;
  uint64_t best_size = ~0ULL;
  for (uint32_t i = 0; i < h->free_count; i++) {
    uint64_t fs = s->freelist[i].size;
    if (fs >= need && fs < best_size) {
      best = i;
      best_size = fs;
      if (fs == need) break;
    }
  }
  if (best != UINT32_MAX) {
    FreeBlock b = s->freelist[best];
    uint64_t rem = b.size - need;
    if (rem > 0) {
      // Keep the exact remainder (even slivers): absorbing it would make
      // the reserved size differ from the entry's recorded capacity, so
      // a later free would strand the tail bytes forever. Coalescing on
      // free merges slivers back into neighbors.
      s->freelist[best].offset = b.offset + need;
      s->freelist[best].size = rem;
      h->free_bytes -= need;
    } else {
      s->freelist[best] = s->freelist[--h->free_count];
      h->free_bytes -= need;
    }
    return b.offset;
  }
  uint64_t off = align8(h->alloc_cursor);
  if (off + need > h->arena_size) return 0;
  h->alloc_cursor = off + need;
  return off;
}

void arena_free(Store* s, uint64_t off, uint64_t size) {
  // Caller holds table_mu. Coalesce with free neighbors, give back blocks
  // that touch the bump cursor, park the rest in the free list.
  if (!off || !size) return;
  Header* h = s->hdr;
  uint64_t need = align8(size);
  for (uint32_t i = 0; i < h->free_count;) {
    FreeBlock* f = &s->freelist[i];
    if (f->offset + f->size == off) {
      off = f->offset;
      need += f->size;
      h->free_bytes -= f->size;
      *f = s->freelist[--h->free_count];
      i = 0;  // the grown block may now touch an already-scanned entry
      continue;
    }
    if (off + need == f->offset) {
      need += f->size;
      h->free_bytes -= f->size;
      *f = s->freelist[--h->free_count];
      i = 0;
      continue;
    }
    i++;
  }
  if (off + need == align8(h->alloc_cursor)) {
    h->alloc_cursor = off;  // retreat the bump cursor
    return;
  }
  if (h->free_count < kMaxFreeBlocks) {
    s->freelist[h->free_count].offset = off;
    s->freelist[h->free_count].size = need;
    h->free_count++;
    h->free_bytes += need;
  }
  // List full: the block leaks until the store is recreated.
}

void shared_mutex_init(pthread_mutex_t* mu) {
  pthread_mutexattr_t at;
  pthread_mutexattr_init(&at);
  pthread_mutexattr_setpshared(&at, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&at, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(mu, &at);
  pthread_mutexattr_destroy(&at);
}

void shared_cond_init(pthread_cond_t* cv) {
  pthread_condattr_t at;
  pthread_condattr_init(&at);
  pthread_condattr_setpshared(&at, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(cv, &at);
  pthread_condattr_destroy(&at);
}

int lock_robust(pthread_mutex_t* mu) {
  int rc = pthread_mutex_lock(mu);
  if (rc == EOWNERDEAD) {  // previous owner died: state is consistent
    pthread_mutex_consistent(mu);
    rc = 0;
  }
  return rc;
}

timespec deadline_from_ms(int64_t timeout_ms) {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) { ts.tv_sec++; ts.tv_nsec -= 1000000000L; }
  return ts;
}

}  // namespace

extern "C" {

// Error codes.
enum {
  RTN_OK = 0,
  RTN_ERR_EXISTS = -1,
  RTN_ERR_NOT_FOUND = -2,
  RTN_ERR_FULL = -3,
  RTN_ERR_TIMEOUT = -4,
  RTN_ERR_CLOSED = -5,
  RTN_ERR_STATE = -6,
  RTN_ERR_SYS = -7,
};

void* rtn_store_create(const char* name, uint64_t arena_size,
                       uint32_t max_objects) {
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t table_bytes = sizeof(Entry) * (uint64_t)max_objects;
  uint64_t free_bytes_sz = align8(sizeof(FreeBlock) * (uint64_t)kMaxFreeBlocks);
  uint64_t total = align8(sizeof(Header)) + free_bytes_sz
                   + align8(table_bytes) + arena_size;
  if (ftruncate(fd, (off_t)total) != 0) { close(fd); return nullptr; }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;

  Store* s = new Store();
  s->hdr = (Header*)mem;
  s->freelist = (FreeBlock*)((uint8_t*)mem + align8(sizeof(Header)));
  s->table = (Entry*)((uint8_t*)mem + align8(sizeof(Header)) + free_bytes_sz);
  s->base = (uint8_t*)s->table + align8(table_bytes);
  s->mapped_size = total;
  s->owner = 1;
  strncpy(s->name, name, sizeof(s->name) - 1);

  memset(s->hdr, 0, sizeof(Header));
  memset(s->freelist, 0, free_bytes_sz);
  memset(s->table, 0, table_bytes);
  s->hdr->magic = kMagic;
  s->hdr->arena_size = arena_size;
  s->hdr->alloc_cursor = 8;  // offset 0 is reserved: alloc returns 0 = fail
  s->hdr->max_objects = max_objects;
  shared_mutex_init(&s->hdr->table_mu);
  return s;
}

void* rtn_store_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
  void* mem = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* hdr = (Header*)mem;
  if (hdr->magic != kMagic) { munmap(mem, (size_t)st.st_size); return nullptr; }
  Store* s = new Store();
  s->hdr = hdr;
  uint64_t table_bytes = sizeof(Entry) * (uint64_t)hdr->max_objects;
  uint64_t free_bytes_sz = align8(sizeof(FreeBlock) * (uint64_t)kMaxFreeBlocks);
  s->freelist = (FreeBlock*)((uint8_t*)mem + align8(sizeof(Header)));
  s->table = (Entry*)((uint8_t*)mem + align8(sizeof(Header)) + free_bytes_sz);
  s->base = (uint8_t*)s->table + align8(table_bytes);
  s->mapped_size = (uint64_t)st.st_size;
  s->owner = 0;
  strncpy(s->name, name, sizeof(s->name) - 1);
  return s;
}

void rtn_store_close(void* handle) {
  Store* s = (Store*)handle;
  if (!s) return;
  int owner = s->owner;
  char name[256];
  strncpy(name, s->name, sizeof(name));
  munmap((void*)s->hdr, s->mapped_size);
  if (owner) shm_unlink(name);
  delete s;
}

uint64_t rtn_store_capacity(void* handle) {
  return ((Store*)handle)->hdr->arena_size;
}

uint64_t rtn_store_used(void* handle) {
  Header* h = ((Store*)handle)->hdr;
  return h->alloc_cursor - h->free_bytes;
}

uint64_t rtn_store_num_objects(void* handle) {
  return ((Store*)handle)->hdr->used_objects;
}

// ---- immutable objects ----------------------------------------------------

int rtn_put(void* handle, uint64_t id, const uint8_t* data, uint64_t len) {
  Store* s = (Store*)handle;
  lock_robust(&s->hdr->table_mu);
  Entry* existing = find_slot(s, id, false);
  if (existing) { pthread_mutex_unlock(&s->hdr->table_mu); return RTN_ERR_EXISTS; }
  Entry* e = find_slot(s, id, true);
  if (!e) { pthread_mutex_unlock(&s->hdr->table_mu); return RTN_ERR_FULL; }
  uint64_t off = arena_alloc(s, len);
  if (!off && len > 0) { pthread_mutex_unlock(&s->hdr->table_mu); return RTN_ERR_FULL; }
  e->id = id;
  e->offset = off;
  e->capacity = align8(len);  // what arena_alloc reserved (arena_free needs it)
  e->size = len;
  e->ctrl_offset = 0;
  e->state = kSealed;
  s->hdr->used_objects++;
  memcpy(s->base + off, data, len);
  pthread_mutex_unlock(&s->hdr->table_mu);
  return RTN_OK;
}

int rtn_get(void* handle, uint64_t id, uint8_t** out_ptr, uint64_t* out_len) {
  Store* s = (Store*)handle;
  lock_robust(&s->hdr->table_mu);
  Entry* e = find_slot(s, id, false);
  if (!e || e->state != kSealed) {
    pthread_mutex_unlock(&s->hdr->table_mu);
    return RTN_ERR_NOT_FOUND;
  }
  *out_ptr = s->base + e->offset;
  *out_len = e->size;
  pthread_mutex_unlock(&s->hdr->table_mu);
  return RTN_OK;
}

int rtn_contains(void* handle, uint64_t id) {
  Store* s = (Store*)handle;
  lock_robust(&s->hdr->table_mu);
  Entry* e = find_slot(s, id, false);
  int ok = (e != nullptr);
  pthread_mutex_unlock(&s->hdr->table_mu);
  return ok;
}

int rtn_delete(void* handle, uint64_t id) {
  Store* s = (Store*)handle;
  lock_robust(&s->hdr->table_mu);
  Entry* e = find_slot(s, id, false);
  if (!e) { pthread_mutex_unlock(&s->hdr->table_mu); return RTN_ERR_NOT_FOUND; }
  if (e->state == kMutable) {  // mutable objects go through rtn_mo_destroy
    pthread_mutex_unlock(&s->hdr->table_mu);
    return RTN_ERR_STATE;
  }
  e->state = kTombstone;
  arena_free(s, e->offset, e->capacity);
  s->hdr->used_objects--;
  pthread_mutex_unlock(&s->hdr->table_mu);
  return RTN_OK;
}

// ---- mutable objects (channel substrate) ----------------------------------

int rtn_mo_create(void* handle, uint64_t id, uint64_t max_size,
                  uint32_t num_readers) {
  Store* s = (Store*)handle;
  lock_robust(&s->hdr->table_mu);
  if (find_slot(s, id, false)) {
    pthread_mutex_unlock(&s->hdr->table_mu);
    return RTN_ERR_EXISTS;
  }
  Entry* e = find_slot(s, id, true);
  if (!e) { pthread_mutex_unlock(&s->hdr->table_mu); return RTN_ERR_FULL; }
  uint64_t ctrl_off = arena_alloc(s, sizeof(MutableCtrl));
  uint64_t pay_off = arena_alloc(s, max_size);
  if (!ctrl_off || (!pay_off && max_size > 0)) {
    pthread_mutex_unlock(&s->hdr->table_mu);
    return RTN_ERR_FULL;
  }
  MutableCtrl* c = (MutableCtrl*)(s->base + ctrl_off);
  memset(c, 0, sizeof(MutableCtrl));
  shared_mutex_init(&c->mu);
  shared_cond_init(&c->cv);
  c->num_readers = num_readers;
  e->id = id;
  e->offset = pay_off;
  e->capacity = max_size;
  e->size = 0;
  e->ctrl_offset = ctrl_off;
  e->state = kMutable;
  s->hdr->used_objects++;
  pthread_mutex_unlock(&s->hdr->table_mu);
  return RTN_OK;
}

static int mo_lookup(Store* s, uint64_t id, Entry** out_e, MutableCtrl** out_c) {
  lock_robust(&s->hdr->table_mu);
  Entry* e = find_slot(s, id, false);
  if (!e || e->state != kMutable) {
    pthread_mutex_unlock(&s->hdr->table_mu);
    return RTN_ERR_NOT_FOUND;
  }
  *out_e = e;
  *out_c = (MutableCtrl*)(s->base + e->ctrl_offset);
  pthread_mutex_unlock(&s->hdr->table_mu);
  return RTN_OK;
}

// Write blocks until every reader consumed the previous version (single
// outstanding version — the reference's mutable-object protocol).
int rtn_mo_write(void* handle, uint64_t id, const uint8_t* data,
                 uint64_t len, int64_t timeout_ms) {
  Store* s = (Store*)handle;
  Entry* e; MutableCtrl* c;
  int rc = mo_lookup(s, id, &e, &c);
  if (rc != RTN_OK) return rc;
  if (len > e->capacity) return RTN_ERR_FULL;
  timespec dl = deadline_from_ms(timeout_ms);
  lock_robust(&c->mu);
  while (c->reads_remaining > 0 && !c->closed) {
    if (pthread_cond_timedwait(&c->cv, &c->mu, &dl) == ETIMEDOUT) {
      pthread_mutex_unlock(&c->mu);
      return RTN_ERR_TIMEOUT;
    }
  }
  if (c->closed) { pthread_mutex_unlock(&c->mu); return RTN_ERR_CLOSED; }
  memcpy(s->base + e->offset, data, len);
  c->payload_size = len;
  c->version++;
  c->reads_remaining = c->num_readers;
  pthread_cond_broadcast(&c->cv);
  pthread_mutex_unlock(&c->mu);
  return RTN_OK;
}

// Read blocks until a version > last_seen exists; returns that version.
// Copies out under the lock (payload is overwritten by the next write).
int rtn_mo_read(void* handle, uint64_t id, uint64_t last_seen,
                uint8_t* out_buf, uint64_t buf_cap, uint64_t* out_len,
                uint64_t* out_version, int64_t timeout_ms) {
  Store* s = (Store*)handle;
  Entry* e; MutableCtrl* c;
  int rc = mo_lookup(s, id, &e, &c);
  if (rc != RTN_OK) return rc;
  timespec dl = deadline_from_ms(timeout_ms);
  lock_robust(&c->mu);
  while (c->version <= last_seen && !c->closed) {
    if (pthread_cond_timedwait(&c->cv, &c->mu, &dl) == ETIMEDOUT) {
      pthread_mutex_unlock(&c->mu);
      return RTN_ERR_TIMEOUT;
    }
  }
  if (c->version <= last_seen && c->closed) {
    pthread_mutex_unlock(&c->mu);
    return RTN_ERR_CLOSED;
  }
  if (c->closed == 2) {
    // Destroyed (payload arena reclaimed): no drain — the bytes at
    // e->offset may already belong to another object.
    pthread_mutex_unlock(&c->mu);
    return RTN_ERR_CLOSED;
  }
  if (c->payload_size > buf_cap) {
    pthread_mutex_unlock(&c->mu);
    return RTN_ERR_FULL;
  }
  memcpy(out_buf, s->base + e->offset, c->payload_size);
  *out_len = c->payload_size;
  *out_version = c->version;
  if (c->reads_remaining > 0) {
    c->reads_remaining--;
    if (c->reads_remaining == 0) pthread_cond_broadcast(&c->cv);
  }
  pthread_mutex_unlock(&c->mu);
  return RTN_OK;
}

int rtn_mo_close(void* handle, uint64_t id) {
  Store* s = (Store*)handle;
  Entry* e; MutableCtrl* c;
  int rc = mo_lookup(s, id, &e, &c);
  if (rc != RTN_OK) return rc;
  lock_robust(&c->mu);
  c->closed = 1;
  pthread_cond_broadcast(&c->cv);
  pthread_mutex_unlock(&c->mu);
  return RTN_OK;
}

int rtn_mo_destroy(void* handle, uint64_t id) {
  // Close + reclaim the payload arena. The MutableCtrl block (mutex/cv
  // memory a blocked peer may still reference) is intentionally leaked;
  // read/write after close observe `closed` under the ctrl mutex and
  // never touch the freed payload.
  Store* s = (Store*)handle;
  Entry* e; MutableCtrl* c;
  int rc = mo_lookup(s, id, &e, &c);
  if (rc != RTN_OK) return rc;
  lock_robust(&c->mu);
  c->closed = 2;  // destroyed: readers must not drain from the payload
  pthread_cond_broadcast(&c->cv);
  pthread_mutex_unlock(&c->mu);
  lock_robust(&s->hdr->table_mu);
  if (e->id == id && e->state == kMutable) {
    e->state = kTombstone;
    arena_free(s, e->offset, e->capacity);
    s->hdr->used_objects--;
  }
  pthread_mutex_unlock(&s->hdr->table_mu);
  return RTN_OK;
}

}  // extern "C"
