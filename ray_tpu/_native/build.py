"""Build + load the native library (ctypes, no pybind11 — per environment:
Python↔C++ binding via ctypes over a C ABI)."""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_SOURCES = ["object_store.cc", "task_queue.cc"]
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _src_dir() -> str:
    return os.path.dirname(os.path.abspath(__file__))


def _cache_dir() -> str:
    from ray_tpu._private.config import GlobalConfig

    d = GlobalConfig.native_cache or os.path.join(
        os.path.expanduser("~"), ".cache", "ray_tpu")
    os.makedirs(d, exist_ok=True)
    return d


def _build() -> Optional[str]:
    srcs = [os.path.join(_src_dir(), s) for s in _SOURCES]
    h = hashlib.sha256()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    so_path = os.path.join(
        _cache_dir(), f"libray_tpu_native-{h.hexdigest()[:16]}.so")
    if os.path.exists(so_path):
        return so_path
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-pthread", "-std=c++17",
           *srcs, "-o", so_path + ".tmp", "-lrt"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, FileNotFoundError,
            subprocess.TimeoutExpired):
        return None
    os.replace(so_path + ".tmp", so_path)
    return so_path


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u64, u32, i64, i32 = (ctypes.c_uint64, ctypes.c_uint32,
                          ctypes.c_int64, ctypes.c_int32)
    p = ctypes.c_void_p
    u8p = ctypes.POINTER(ctypes.c_uint8)

    lib.rtn_store_create.restype = p
    lib.rtn_store_create.argtypes = [ctypes.c_char_p, u64, u32]
    lib.rtn_store_open.restype = p
    lib.rtn_store_open.argtypes = [ctypes.c_char_p]
    lib.rtn_store_close.argtypes = [p]
    lib.rtn_store_capacity.restype = u64
    lib.rtn_store_capacity.argtypes = [p]
    lib.rtn_store_used.restype = u64
    lib.rtn_store_used.argtypes = [p]
    lib.rtn_store_num_objects.restype = u64
    lib.rtn_store_num_objects.argtypes = [p]
    lib.rtn_put.restype = i32
    lib.rtn_put.argtypes = [p, u64, ctypes.c_char_p, u64]
    lib.rtn_get.restype = i32
    lib.rtn_get.argtypes = [p, u64, ctypes.POINTER(u8p),
                            ctypes.POINTER(u64)]
    lib.rtn_contains.restype = i32
    lib.rtn_contains.argtypes = [p, u64]
    lib.rtn_delete.restype = i32
    lib.rtn_delete.argtypes = [p, u64]
    lib.rtn_mo_create.restype = i32
    lib.rtn_mo_create.argtypes = [p, u64, u64, u32]
    lib.rtn_mo_write.restype = i32
    lib.rtn_mo_write.argtypes = [p, u64, ctypes.c_char_p, u64, i64]
    lib.rtn_mo_read.restype = i32
    lib.rtn_mo_read.argtypes = [p, u64, u64, ctypes.c_char_p, u64,
                                ctypes.POINTER(u64), ctypes.POINTER(u64),
                                i64]
    lib.rtn_mo_close.restype = i32
    lib.rtn_mo_close.argtypes = [p, u64]
    lib.rtn_mo_destroy.restype = i32
    lib.rtn_mo_destroy.argtypes = [p, u64]

    lib.rtn_tq_create.restype = p
    lib.rtn_tq_create.argtypes = [u32, u32]
    lib.rtn_tq_destroy.argtypes = [p]
    lib.rtn_tq_add_task.restype = i32
    lib.rtn_tq_add_task.argtypes = [p, u32]
    lib.rtn_tq_add_edge.restype = i32
    lib.rtn_tq_add_edge.argtypes = [p, u32, u32]
    lib.rtn_tq_seal.restype = i32
    lib.rtn_tq_seal.argtypes = [p]
    lib.rtn_tq_complete.restype = i32
    lib.rtn_tq_complete.argtypes = [p, ctypes.POINTER(u32), u32]
    lib.rtn_tq_pop_wave.restype = i32
    lib.rtn_tq_pop_wave.argtypes = [p, ctypes.POINTER(u32), u32, i64]
    lib.rtn_tq_num_done.restype = u32
    lib.rtn_tq_num_done.argtypes = [p]
    lib.rtn_tq_num_tasks.restype = u32
    lib.rtn_tq_num_tasks.argtypes = [p]

    lib.rtn_dq_create.restype = p
    lib.rtn_dq_create.argtypes = [u32, u32]
    lib.rtn_dq_destroy.argtypes = [p]
    lib.rtn_dq_alloc.restype = u64
    lib.rtn_dq_alloc.argtypes = [p]
    lib.rtn_dq_add_dep.restype = i32
    lib.rtn_dq_add_dep.argtypes = [p, u64, u64]
    lib.rtn_dq_commit.restype = i32
    lib.rtn_dq_commit.argtypes = [p, u64]
    lib.rtn_dq_complete.restype = i32
    lib.rtn_dq_complete.argtypes = [p, u64]
    lib.rtn_dq_abort.restype = i32
    lib.rtn_dq_abort.argtypes = [p, u64]
    lib.rtn_dq_pop.restype = i32
    lib.rtn_dq_pop.argtypes = [p, ctypes.POINTER(u64), u32, i64]
    lib.rtn_dq_wake.argtypes = [p]
    lib.rtn_dq_num_pending.restype = u64
    lib.rtn_dq_num_pending.argtypes = [p]
    lib.rtn_dq_num_done.restype = u64
    lib.rtn_dq_num_done.argtypes = [p]
    return lib


def load_native() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        so = _build()
        if so is None:
            _load_failed = True
            return None
        try:
            _lib = _bind(ctypes.CDLL(so))
        except OSError:
            _load_failed = True
            return None
        return _lib


def native_available() -> bool:
    return load_native() is not None
