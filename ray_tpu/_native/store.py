"""Pythonic wrappers over the native library (object store, mutable
channels, task queue)."""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional

import numpy as np

from ray_tpu._native.build import load_native
from ray_tpu.exceptions import ChannelError, ChannelTimeoutError

_ERRS = {
    -1: "exists", -2: "not found", -3: "full", -4: "timeout",
    -5: "closed", -6: "bad state", -7: "system error",
}


class NativeError(RuntimeError):
    def __init__(self, code: int, op: str):
        super().__init__(f"native {op} failed: {_ERRS.get(code, code)}")
        self.code = code
        self.op = op

    def __reduce__(self):
        # Default exception reduce would call __init__(message) and crash
        # when the error crosses a process boundary.
        return (NativeError, (self.code, self.op))


def _check(code: int, op: str):
    if code == -4:
        raise ChannelTimeoutError(f"native {op} timed out")
    if code == -5:
        raise ChannelError(f"native {op}: channel closed")
    if code != 0:
        raise NativeError(code, op)


class NativeObjectStore:
    """Shared-memory object store (plasma-parity surface: put/get/contains/
    delete + mutable objects). ``create`` owns the segment; ``open``
    attaches from another process."""

    def __init__(self, handle, lib, owner: bool):
        self._h = handle
        self._lib = lib
        self._owner = owner

    @staticmethod
    def create(name: Optional[str] = None, capacity: int = 64 << 20,
               max_objects: int = 4096) -> "NativeObjectStore":
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable (no g++?)")
        name = name or f"/ray_tpu_store_{os.getpid()}_{id(lib) & 0xffff}"
        h = lib.rtn_store_create(name.encode(), capacity, max_objects)
        if not h:
            raise RuntimeError(f"failed to create shm store {name}")
        store = NativeObjectStore(h, lib, owner=True)
        store.name = name
        return store

    @staticmethod
    def open(name: str) -> "NativeObjectStore":
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        h = lib.rtn_store_open(name.encode())
        if not h:
            raise RuntimeError(f"failed to open shm store {name}")
        store = NativeObjectStore(h, lib, owner=False)
        store.name = name
        return store

    def close(self):
        if self._h:
            self._lib.rtn_store_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------------- objects
    def put(self, object_id: int, data: bytes):
        _check(self._lib.rtn_put(self._h, object_id, data, len(data)),
               "put")

    def get(self, object_id: int) -> bytes:
        ptr = ctypes.POINTER(ctypes.c_uint8)()
        ln = ctypes.c_uint64()
        _check(self._lib.rtn_get(self._h, object_id, ctypes.byref(ptr),
                                 ctypes.byref(ln)), "get")
        return ctypes.string_at(ptr, ln.value)

    def get_view(self, object_id: int) -> memoryview:
        """Zero-copy view into the shm segment (valid until delete)."""
        ptr = ctypes.POINTER(ctypes.c_uint8)()
        ln = ctypes.c_uint64()
        _check(self._lib.rtn_get(self._h, object_id, ctypes.byref(ptr),
                                 ctypes.byref(ln)), "get")
        arr = np.ctypeslib.as_array(ptr, shape=(ln.value,))
        return memoryview(arr)

    def contains(self, object_id: int) -> bool:
        return bool(self._lib.rtn_contains(self._h, object_id))

    def delete(self, object_id: int):
        _check(self._lib.rtn_delete(self._h, object_id), "delete")

    def stats(self) -> dict:
        return {
            "capacity": self._lib.rtn_store_capacity(self._h),
            "used": self._lib.rtn_store_used(self._h),
            "num_objects": self._lib.rtn_store_num_objects(self._h),
        }

    # ----------------------------------------------------- mutable objects
    def mo_create(self, object_id: int, max_size: int,
                  num_readers: int = 1):
        _check(self._lib.rtn_mo_create(self._h, object_id, max_size,
                                       num_readers), "mo_create")

    def mo_write(self, object_id: int, data: bytes,
                 timeout_s: float = 60.0):
        _check(self._lib.rtn_mo_write(self._h, object_id, data, len(data),
                                      int(timeout_s * 1000)), "mo_write")

    def mo_read(self, object_id: int, last_seen: int, max_size: int,
                timeout_s: float = 60.0) -> (bytes, int):
        buf = ctypes.create_string_buffer(max_size)
        ln = ctypes.c_uint64()
        ver = ctypes.c_uint64()
        _check(self._lib.rtn_mo_read(
            self._h, object_id, last_seen, buf, max_size,
            ctypes.byref(ln), ctypes.byref(ver),
            int(timeout_s * 1000)), "mo_read")
        return buf.raw[:ln.value], ver.value

    def mo_close(self, object_id: int):
        _check(self._lib.rtn_mo_close(self._h, object_id), "mo_close")

    def mo_destroy(self, object_id: int):
        """Close + reclaim the payload arena (owner teardown path)."""
        _check(self._lib.rtn_mo_destroy(self._h, object_id), "mo_destroy")


class NativeMutableChannel:
    """Channel API over a native mutable object — the cross-process
    SharedMemoryChannel (channels/channel.py Channel protocol)."""

    _COUNTER = [0]

    def __init__(self, store: NativeObjectStore, object_id: Optional[int]
                 = None, max_size: int = 1 << 20, num_readers: int = 1,
                 create: bool = True):
        self._store = store
        if object_id is None:
            NativeMutableChannel._COUNTER[0] += 1
            object_id = (os.getpid() << 20) | NativeMutableChannel._COUNTER[0]
        self.object_id = object_id
        self.max_size = max_size
        if create:
            store.mo_create(object_id, max_size, num_readers)
        self._last_seen = [0] * num_readers

    def write(self, value, timeout: Optional[float] = None):
        import pickle

        data = pickle.dumps(value, protocol=5)
        try:
            self._store.mo_write(self.object_id, data,
                                 timeout_s=timeout if timeout else 60.0)
        except NativeError as e:
            if e.code == -2:  # destroyed channel == closed to peers
                raise ChannelError("channel destroyed") from None
            raise

    def read(self, reader_id: int = 0, timeout: Optional[float] = None):
        import pickle

        try:
            data, ver = self._store.mo_read(
                self.object_id, self._last_seen[reader_id], self.max_size,
                timeout_s=timeout if timeout else 60.0)
        except NativeError as e:
            if e.code == -2:  # destroyed channel == closed to peers
                raise ChannelError("channel destroyed") from None
            raise
        self._last_seen[reader_id] = ver
        return pickle.loads(data)

    def close(self):
        """Signal EOF; committed data stays readable (drain semantics)."""
        try:
            self._store.mo_close(self.object_id)
        except NativeError:
            pass

    def destroy(self):
        """Close + reclaim the payload arena — only when no peer can still
        drain (e.g. the worker process on the other end is dead)."""
        try:
            self._store.mo_destroy(self.object_id)
        except NativeError:
            pass


class NativeTaskQueue:
    """Dependency-tracking ready queue (the C++ scheduler hot loop)."""

    def __init__(self, max_tasks: int, max_edges: int):
        self._lib = load_native()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._q = self._lib.rtn_tq_create(max_tasks, max_edges)
        self._sealed = False

    def add_task(self, task_id: int):
        if self._lib.rtn_tq_add_task(self._q, task_id) != 0:
            raise ValueError(f"bad task id {task_id} (or sealed)")

    def add_edge(self, src: int, dst: int):
        if self._lib.rtn_tq_add_edge(self._q, src, dst) != 0:
            raise ValueError(f"bad edge {src}->{dst} (or sealed/full)")

    def seal(self):
        if self._lib.rtn_tq_seal(self._q) != 0:
            raise RuntimeError("already sealed")
        self._sealed = True

    def complete(self, task_ids: List[int]):
        arr = (ctypes.c_uint32 * len(task_ids))(*task_ids)
        self._lib.rtn_tq_complete(self._q, arr, len(task_ids))

    def pop_wave(self, max_tasks: int = 1024,
                 timeout_s: float = 1.0) -> List[int]:
        out = (ctypes.c_uint32 * max_tasks)()
        n = self._lib.rtn_tq_pop_wave(self._q, out, max_tasks,
                                      int(timeout_s * 1000))
        return list(out[:n])

    @property
    def num_done(self) -> int:
        return self._lib.rtn_tq_num_done(self._q)

    @property
    def num_tasks(self) -> int:
        return self._lib.rtn_tq_num_tasks(self._q)

    def __del__(self):
        try:
            if getattr(self, "_q", None):
                self._lib.rtn_tq_destroy(self._q)
                self._q = None
        except Exception:  # noqa: BLE001
            pass


class NativeDynQueue:
    """Incremental dependency queue (the live scheduler's native hot loop).

    Handles are opaque uint64s with an embedded generation so completed
    slots recycle safely; ``add_dep`` against an already-completed producer
    is a no-op (the dependency is satisfied).
    """

    def __init__(self, max_tasks: int = 1 << 16, max_edges: int = 1 << 18):
        self._lib = load_native()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._q = self._lib.rtn_dq_create(max_tasks, max_edges)

    def alloc(self) -> int:
        h = self._lib.rtn_dq_alloc(self._q)
        if h == 0:
            raise MemoryError("dynamic task queue is full")
        return h

    def add_dep(self, task: int, dep: int):
        rc = self._lib.rtn_dq_add_dep(self._q, task, dep)
        if rc == -3:
            raise MemoryError("dynamic task queue edge table is full")
        if rc != 0:
            raise ValueError(f"bad task handle {task:#x}")

    def commit(self, task: int):
        if self._lib.rtn_dq_commit(self._q, task) != 0:
            raise ValueError(f"bad task handle {task:#x}")

    def complete(self, task: int):
        if self._lib.rtn_dq_complete(self._q, task) != 0:
            raise ValueError(f"bad/uncommitted task handle {task:#x}")

    def abort(self, task: int):
        """Abandon a task that never ran (registration unwind); its slot is
        recycled and edges into it go stale via the generation tag."""
        if self._lib.rtn_dq_abort(self._q, task) != 0:
            raise ValueError(f"bad task handle {task:#x}")

    def pop(self, max_tasks: int = 1024, timeout_s: float = 0.2) -> List[int]:
        out = (ctypes.c_uint64 * max_tasks)()
        n = self._lib.rtn_dq_pop(self._q, out, max_tasks,
                                 int(timeout_s * 1000))
        return list(out[:n])

    def wake(self):
        self._lib.rtn_dq_wake(self._q)

    @property
    def num_pending(self) -> int:
        return self._lib.rtn_dq_num_pending(self._q)

    @property
    def num_done(self) -> int:
        return self._lib.rtn_dq_num_done(self._q)

    def __del__(self):
        try:
            if getattr(self, "_q", None):
                self._lib.rtn_dq_destroy(self._q)
                self._q = None
        except Exception:  # noqa: BLE001
            pass
