"""Native (C++) runtime components, loaded via ctypes.

Auto-compiles ``libray_tpu_native.so`` with g++ on first import (cached in
``~/.cache/ray_tpu``, keyed by source hash). Gate: ``native_available()``
is False when no toolchain exists — every consumer has a pure-Python
fallback, so the framework degrades rather than breaks.
"""

from ray_tpu._native.build import load_native, native_available
from ray_tpu._native.store import (
    NativeObjectStore,
    NativeMutableChannel,
    NativeTaskQueue,
)

__all__ = [
    "NativeMutableChannel",
    "NativeObjectStore",
    "NativeTaskQueue",
    "load_native",
    "native_available",
]
