"""Pipeline parallelism: GPipe microbatching over the ``pp`` mesh axis.

Absent from the reference (SURVEY.md §2.4 — integration-only). TPU-native
formulation: every pp-shard holds one stage's parameters; activations hop
stage→stage via ``lax.ppermute`` inside a ``fori_loop`` over
``n_stages + n_microbatches - 1`` ticks (the bubble is the standard GPipe
cost). Autodiff is free: the transpose of ppermute is the reverse
permute, so backward runs the pipeline in reverse without extra code.

Call inside ``shard_map`` over ``pp``; stage params must already be the
local stage's slice. Activations may be any pytree (every leaf needs a
leading microbatch axis in ``microbatches``).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_spmd(
    stage_fn: Callable[[Any, Any], Any],
    stage_params: Any,
    microbatches: Any,
    *,
    axis_name: str = "pp",
) -> Any:
    """Run ``stage_fn`` as a GPipe pipeline.

    stage_fn(stage_params, act) -> act' with act/act' the same pytree
    structure and leaf shapes (the inter-stage activation bucket).
    ``microbatches``: pytree with leading axis M on every leaf, present on
    every shard (only stage 0 reads it). Returns the same pytree — the last
    stage's outputs, broadcast to all shards via psum so downstream loss
    code is uniform.
    """
    n = lax.axis_size(axis_name)
    M = jax.tree.leaves(microbatches)[0].shape[0]
    if n == 1:
        return jax.vmap(lambda a: stage_fn(stage_params, a))(microbatches)
    stage = lax.axis_index(axis_name)
    total = n + M - 1
    perm = [(j, (j + 1) % n) for j in range(n)]

    def _index(tree, i):
        return jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
            tree)

    def tick(t, carry):
        act_in, outputs = carry
        # Stage 0 injects microbatch t (clamped; inactive ticks compute
        # values that are never written anywhere).
        x0 = _index(microbatches, jnp.clip(t, 0, M - 1))
        inp = jax.tree.map(
            lambda a, b: jnp.where(stage == 0, a, b), x0, act_in)
        out = stage_fn(stage_params, inp)
        out_idx = t - (n - 1)
        is_valid = (stage == n - 1) & (out_idx >= 0) & (out_idx < M)
        safe = jnp.clip(out_idx, 0, M - 1)
        prev = _index(outputs, safe)
        outputs = jax.tree.map(
            lambda buf, o, p: lax.dynamic_update_index_in_dim(
                buf, jnp.where(is_valid, o, p), safe, 0),
            outputs, out, prev)
        act_next = jax.tree.map(
            lambda a: lax.ppermute(a, axis_name, perm), out)
        return act_next, outputs

    act0 = _index(jax.tree.map(jnp.zeros_like, microbatches), 0)
    outs0 = jax.tree.map(jnp.zeros_like, microbatches)
    _, outputs = lax.fori_loop(0, total, tick, (act0, outs0))
    # Only the last stage holds real outputs; broadcast so every shard
    # returns the same value (grad of psum = identity per shard — correct).
    outputs = jax.tree.map(
        lambda o: lax.psum(jnp.where(stage == n - 1, o, jnp.zeros_like(o)),
                           axis_name),
        outputs)
    return outputs
