"""Device mesh construction and axis conventions.

Axis vocabulary (fixed across the framework):

- ``dp``   data parallel (batch sharding; gradients all-reduced over it)
- ``fsdp`` fully-sharded data parallel (params sharded, all-gathered per layer)
- ``pp``   pipeline parallel (layer stages; activations ppermute'd)
- ``tp``   tensor parallel (hidden/head sharding inside matmuls)
- ``sp``   sequence/context parallel (ring attention / Ulysses over tokens)
- ``ep``   expert parallel (MoE token all_to_all)

Reference role: replaces Ray Train's torch process-group setup
(python/ray/train/torch/config.py [unverified]) and the NCCL group bootstrap
in python/ray/util/collective — on TPU the "process group" is just a Mesh and
the collectives are compiled into the program.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import threading
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "fsdp", "pp", "tp", "sp", "ep")

_local = threading.local()


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes for each mesh axis; -1 on at most one axis means "absorb the rest".

    Unspecified axes default to 1 so every sharding annotation in the
    framework is valid on any mesh (a size-1 axis is a no-op shard).
    """

    dp: int = -1
    fsdp: int = 1
    pp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    def sizes(self, n_devices: int) -> Tuple[int, ...]:
        vals = [self.dp, self.fsdp, self.pp, self.tp, self.sp, self.ep]
        if vals.count(-1) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(v for v in vals if v != -1)
        if n_devices % fixed:
            raise ValueError(
                f"mesh {vals} does not divide {n_devices} devices")
        if -1 in vals:
            vals[vals.index(-1)] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {vals} uses {fixed} devices, have {n_devices}")
        return tuple(vals)


def make_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    **axis_sizes: int,
) -> Mesh:
    """Build a Mesh over all (or given) devices with the standard axes.

    ``make_mesh(dp=2, tp=4)`` or ``make_mesh(MeshConfig(tp=4))``. Axes are
    laid out innermost-last so that tp/sp/ep (highest-bandwidth-need axes)
    map to adjacent devices on the ICI torus — the device order jax returns
    is torus-major on TPU, so contiguity ≈ ICI proximity.
    """
    if config is None:
        config = MeshConfig(**axis_sizes) if axis_sizes else MeshConfig()
    elif axis_sizes:
        raise ValueError("pass either a MeshConfig or axis kwargs, not both")
    if devices is None:
        import os

        # Pin the device platform explicitly (e.g. tests force "cpu" so the
        # 8-device virtual mesh is used even when a TPU plugin also
        # registered itself as the default backend).
        platform = os.environ.get("RAY_TPU_PLATFORM")
        devices = jax.devices(platform) if platform else jax.devices()
    sizes = config.sizes(len(devices))
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, AXES)


def mesh_shape(mesh: Mesh) -> dict:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def get_mesh() -> Optional[Mesh]:
    """The ambient mesh set by :func:`mesh_context` (or None)."""
    return getattr(_local, "mesh", None)


@contextlib.contextmanager
def mesh_context(mesh: Mesh):
    prev = getattr(_local, "mesh", None)
    _local.mesh = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _local.mesh = prev
