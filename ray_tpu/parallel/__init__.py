"""TPU-native parallelism layer (SURVEY.md §2.4).

The reference scales via actor fleets + NCCL process groups (Ray Train DDP,
`ray.util.collective`); TP/PP/SP/EP exist only through integrations. Here
every strategy is first-class and jax-native: one `Mesh` with axes
(dp, fsdp, pp, tp, sp, ep), `NamedSharding` annotations, and XLA collectives
over ICI — the scaling-book recipe (pick a mesh, annotate shardings, let XLA
insert collectives).
"""

from ray_tpu.parallel.mesh import (
    MeshConfig,
    get_mesh,
    make_mesh,
    mesh_context,
)
from ray_tpu.parallel.sharding import (
    ShardingRules,
    logical_sharding,
    shard_params,
    with_sharding_constraint,
)
from ray_tpu.parallel.ring_attention import ring_attention
from ray_tpu.parallel.ulysses import ulysses_attention
from ray_tpu.parallel.moe import moe_dispatch_combine
from ray_tpu.parallel.pipeline import pipeline_spmd
from ray_tpu.parallel import distributed
from ray_tpu.parallel.distributed import (
    HybridMeshConfig,
    make_hybrid_mesh,
)

__all__ = [
    "MeshConfig",
    "ShardingRules",
    "get_mesh",
    "logical_sharding",
    "make_mesh",
    "mesh_context",
    "moe_dispatch_combine",
    "pipeline_spmd",
    "ring_attention",
    "shard_params",
    "ulysses_attention",
    "with_sharding_constraint",
]
