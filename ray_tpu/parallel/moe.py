"""Expert parallelism: MoE token dispatch/combine over the ``ep`` axis.

Absent from the reference (SURVEY.md §2.4). Top-k router → capacity-bucketed
dense dispatch (static shapes for XLA) → ``all_to_all`` to the expert's
shard → expert MLP → ``all_to_all`` back → weighted combine. Dropped tokens
(over capacity) pass through the residual, standard switch-transformer
semantics.

Call inside ``shard_map`` over the ``ep`` axis with experts sharded on it.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def top1_router(logits: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """logits [T, E] -> (expert_idx [T], gate [T])."""
    idx = jnp.argmax(logits, axis=-1)
    gate = jax.nn.softmax(logits, axis=-1)[jnp.arange(logits.shape[0]), idx]
    return idx, gate


def moe_dispatch_combine(
    x: jax.Array,
    router_logits: jax.Array,
    expert_fn: Callable[[jax.Array], jax.Array],
    *,
    num_experts: int,
    capacity_factor: float = 1.25,
    axis_name: str = "ep",
) -> jax.Array:
    """x per-shard [T, D]; router_logits [T, E_global]. ``expert_fn`` maps
    [E_local, C_total, D] -> [E_local, C_total, D] (vmapped expert MLP over
    this shard's experts). Returns [T, D] combined output."""
    n = lax.axis_size(axis_name)
    T, D = x.shape
    E = num_experts
    if E % n:
        raise ValueError(f"experts {E} not divisible by {axis_name} size {n}")
    e_local = E // n
    cap = max(1, int(capacity_factor * T / E))

    idx, gate = top1_router(router_logits)
    # Position of each token within its expert's capacity bucket.
    onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)        # [T, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot                # 1-based
    pos_in_expert = jnp.sum(pos, axis=-1) - 1                # [T]
    keep = pos_in_expert < cap
    gate = jnp.where(keep, gate, 0.0)

    # Dense dispatch buffer [E, cap, D] on this shard.
    disp = jnp.zeros((E, cap, D), x.dtype)
    safe_pos = jnp.clip(pos_in_expert, 0, cap - 1)
    disp = disp.at[idx, safe_pos].add(
        jnp.where(keep[:, None], x, 0.0))

    # all_to_all: every shard sends its [e_local, cap, D] slab for each peer.
    # [E, cap, D] -> [n, e_local, cap, D] -> exchange over axis ->
    # [n, e_local, cap, D] where leading axis is now source shard.
    disp = disp.reshape(n, e_local, cap, D)
    disp = lax.all_to_all(disp, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    disp = disp.reshape(n, e_local, cap, D)
    # Merge source shards into the capacity axis: [e_local, n*cap, D].
    disp = disp.transpose(1, 0, 2, 3).reshape(e_local, n * cap, D)

    out = expert_fn(disp)                                    # [e_local, n*cap, D]

    # Inverse route: split capacity back per source, all_to_all home.
    out = out.reshape(e_local, n, cap, D).transpose(1, 0, 2, 3)
    out = out.reshape(n, e_local, cap, D)
    out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                         tiled=True)
    out = out.reshape(E, cap, D)

    combined = out[idx, safe_pos] * gate[:, None]
    return jnp.where(keep[:, None], combined, 0.0)


def load_balancing_loss(router_logits: jax.Array, expert_idx: jax.Array,
                        num_experts: int) -> jax.Array:
    """Switch-transformer auxiliary loss: E * <fraction routed> · <router prob>."""
    probs = jax.nn.softmax(router_logits, axis=-1)
    frac = jnp.mean(jax.nn.one_hot(expert_idx, num_experts), axis=0)
    return num_experts * jnp.sum(frac * jnp.mean(probs, axis=0))
