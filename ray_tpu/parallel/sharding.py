"""Logical-axis sharding rules → NamedSharding.

The reference has no native TP/FSDP — params are sharded by torch FSDP or
DeepSpeed inside user code (SURVEY.md §2.4). Here sharding is a first-class
framework concept: model code names its array axes logically ("embed",
"mlp", "heads", ...), a ShardingRules table maps logical names to mesh axes,
and XLA/GSPMD inserts the collectives.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple, Union

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxis = Union[None, str, Tuple[str, ...]]


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """Map from logical array-axis names to mesh axis (or None = replicate).

    Defaults implement the standard megatron-style recipe:
    - batch over (dp, fsdp); sequence over sp (context parallel)
    - embed replicated; per-layer weights sharded on tp along the
      "wide" axis (mlp hidden, attention heads) and on fsdp along the other
      (ZeRO-3 — all-gathered per layer by XLA)
    - experts over ep; pipeline stages over pp (stacked-layer leading axis)
    """

    batch: MeshAxis = ("dp", "fsdp")
    sequence: MeshAxis = "sp"
    embed: MeshAxis = None
    mlp: MeshAxis = "tp"
    heads: MeshAxis = "tp"
    kv_heads: MeshAxis = "tp"
    head_dim: MeshAxis = None
    vocab: MeshAxis = "tp"
    expert: MeshAxis = "ep"
    stage: MeshAxis = "pp"
    fsdp_shard: MeshAxis = "fsdp"  # axis that ZeRO-shards 2D weights

    def spec(self, *logical: Optional[str]) -> P:
        """PartitionSpec for an array whose axes have these logical names."""
        out = []
        for name in logical:
            if name is None:
                out.append(None)
            else:
                out.append(getattr(self, name))
        return P(*out)


def logical_sharding(
    mesh: Mesh, rules: ShardingRules, *logical: Optional[str]
) -> NamedSharding:
    return NamedSharding(mesh, rules.spec(*logical))


def with_sharding_constraint(x, mesh: Optional[Mesh], spec: P):
    """Annotate an intermediate; no-op outside jit or without a mesh."""
    if mesh is None:
        return x
    return lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def constrain_logical(x, mesh: Optional[Mesh],
                      rules: Optional[ShardingRules],
                      *logical: Optional[str]):
    """Constrain an intermediate by LOGICAL axis names (no-op without a
    mesh) — the shared hook the inference path (models/transformer.py,
    ops/paged_attention.py) uses to graft Megatron TP onto cached
    prefill/decode."""
    if mesh is None:
        return x
    r = rules or ShardingRules()
    return with_sharding_constraint(x, mesh, r.spec(*logical))


def shard_params(params: Any, mesh: Mesh, spec_tree: Any) -> Any:
    """Device-put a parameter pytree according to a matching tree of
    PartitionSpecs (as produced by a model's ``param_specs()``)."""
    def _put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(_put, params, spec_tree,
                        is_leaf=lambda x: x is None)


def kv_cache_specs(rules: Optional[ShardingRules] = None) -> dict:
    """PartitionSpec tree for the paged KV pool ``{"k", "v"}`` arrays
    (``[L, num_blocks, block_size, n_kv_heads, head_dim]``): sharded
    along ``n_kv_heads`` so tensor-parallel decode keeps each chip's
    cache shard private to its attention-head shard — block IDS stay
    global (the host block manager is oblivious to the mesh), block
    BYTES never cross chips."""
    r = rules or ShardingRules()
    spec = P(None, None, None, r.kv_heads, None)
    return {"k": spec, "v": spec}


def param_sharding_tree(mesh: Mesh, spec_tree: Any) -> Any:
    """Tree of NamedShardings from a tree of PartitionSpecs (for jit
    in_shardings / out_shardings arguments)."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), spec_tree,
        is_leaf=lambda x: isinstance(x, P))
