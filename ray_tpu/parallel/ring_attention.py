"""Ring attention: exact blockwise attention over a context-parallel axis.

Absent from the reference (SURVEY.md §2.4/§5.7) — long-context is a
first-class capability here. Each sp-shard holds a sequence block of Q/K/V;
K/V blocks rotate around the ring via ``lax.ppermute`` while each device
accumulates its queries' attention online (flash-attention style running
max/sum), so the full O(S^2) score matrix never materializes on one chip and
comm overlaps compute around the ICI ring.

Call inside ``shard_map`` over the ``sp`` axis (see models/transformer.py),
with Q/K/V already sharded on the sequence axis.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attn(q, k, v, bias, scale):
    # q: [B, H, Sq, D], k/v: [B, H, Sk, D] -> scores [B, H, Sq, Sk]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1, keepdims=True)
    # Guard fully-masked rows (all -inf): exp(0)=1 row but weight 0 below.
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o, m_safe, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention with K/V ring-rotated over ``axis_name``.

    Shapes (per shard): q/k/v [B, H, S_local, D]. Requires the global
    sequence laid out contiguously across the axis (shard i holds tokens
    [i*S_local, (i+1)*S_local)). Returns [B, H, S_local, D].
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_local = q.shape[2]

    q_pos = my * s_local + jnp.arange(s_local)

    def causal_bias(kv_shard):
        k_pos = kv_shard * s_local + jnp.arange(s_local)
        mask = q_pos[:, None] >= k_pos[None, :]
        return jnp.where(mask, 0.0, NEG_INF)[None, None]

    def step(i, carry):
        o, m, l, k_cur, v_cur = carry
        kv_shard = (my - i) % n
        bias = causal_bias(kv_shard) if causal else None
        o_i, m_i, l_i = _block_attn(q, k_cur, v_cur, bias, scale)
        # Online softmax merge of (o, m, l) with the new block.
        m_new = jnp.maximum(m, m_i)
        a = jnp.exp(m - m_new)
        b = jnp.exp(m_i - m_new)
        o = o * a + o_i * b
        l = l * a + l_i * b
        # Rotate K/V one hop around the ring (device d -> d+1).
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return o, m_new, l, k_nxt, v_nxt

    o0 = jnp.zeros_like(q)
    m0 = jnp.full(q.shape[:3] + (1,), NEG_INF, q.dtype)
    l0 = jnp.zeros(q.shape[:3] + (1,), q.dtype)
    if n == 1:
        bias = causal_bias(0) if causal else None
        o, _, l = _block_attn(q, k, v, bias, scale)
        return o / jnp.maximum(l, 1e-30)
    o, m, l, _, _ = lax.fori_loop(
        0, n, step, (o0, m0, l0, k, v), unroll=True)
    return o / jnp.maximum(l, 1e-30)


def reference_attention(q, k, v, causal=True, scale=None):
    """Unsharded exact attention, for tests."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)
