"""Multi-host bootstrap + DCN/ICI-aware meshes.

Rebuild of the reference's multi-node communication bootstrap (reference
roles: the NCCL/MPI rendezvous in python/ray/util/collective and Train's
process-group setup [unverified]) the TPU way: processes join a
``jax.distributed`` coordination service, every host contributes its local
chips to one global device view, and parallelism axes are laid out so that
bandwidth-hungry collectives (tp/sp/ep/fsdp) ride ICI within a slice while
only gradient-sync (dp) and pipeline edges (pp) cross the DCN between
hosts — the scaling-book recipe.

Single-host (and the CI's virtual CPU mesh) is the degenerate case:
``initialize()`` is a no-op with process_count == 1 and the hybrid mesh
falls back to a flat mesh, so every code path here runs under the
8-device virtual mesh without real multi-host hardware.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Axes whose collectives must stay on ICI (high bandwidth, in-slice);
# dp/pp tolerate DCN (per-step gradient all-reduce / p2p activations).
ICI_AXES = ("fsdp", "tp", "sp", "ep")
DCN_AXES = ("dp", "pp")

_state = {"initialized": False, "process_id": 0, "num_processes": 1}


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None) -> None:
    """Join the multi-host coordination service (jax.distributed shape).

    Arguments default from the standard environment
    (``RAY_TPU_COORDINATOR_ADDRESS`` / ``RAY_TPU_NUM_PROCESSES`` /
    ``RAY_TPU_PROCESS_ID``, matching upstream JAX's variables when unset).
    With one process (or no coordinator configured) this is a local no-op
    — the single-host paths are unchanged.
    """
    from ray_tpu._private.config import GlobalConfig

    coordinator_address = coordinator_address or \
        GlobalConfig.coordinator_address or None
    num_processes = num_processes if num_processes is not None else int(
        os.environ.get("RAY_TPU_NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("RAY_TPU_PROCESS_ID", "0"))
    if num_processes <= 1 or not coordinator_address:
        _state.update(initialized=True, process_id=0, num_processes=1)
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _state.update(initialized=True, process_id=process_id,
                  num_processes=num_processes)


def shutdown() -> None:
    if _state["num_processes"] > 1:
        try:
            jax.distributed.shutdown()
        except Exception:  # noqa: BLE001 — already down
            pass
    _state.update(initialized=False, process_id=0, num_processes=1)


def is_initialized() -> bool:
    return _state["initialized"]


def process_count() -> int:
    return (jax.process_count() if _state["num_processes"] > 1
            else _state["num_processes"])


def process_index() -> int:
    return (jax.process_index() if _state["num_processes"] > 1
            else _state["process_id"])


@dataclasses.dataclass(frozen=True)
class HybridMeshConfig:
    """Axis sizes split between the DCN tier (across hosts/slices) and the
    ICI tier (within a slice)."""

    dcn: Dict[str, int] = dataclasses.field(default_factory=dict)
    ici: Dict[str, int] = dataclasses.field(default_factory=dict)


def make_hybrid_mesh(config: HybridMeshConfig,
                     devices: Optional[Sequence] = None) -> Mesh:
    """Build a mesh whose axis ORDER encodes the network tier: DCN axes
    (dp, pp) are outermost/slowest-varying — their neighbors sit on other
    hosts — and ICI axes innermost, so XLA lowers their collectives onto
    the intra-slice interconnect. Uses
    ``mesh_utils.create_hybrid_device_mesh`` on real multi-host topologies
    and a flat reshape on one host (where every axis is ICI anyway).
    """
    for name in config.dcn:
        if name not in DCN_AXES:
            raise ValueError(
                f"axis {name!r} must not cross DCN (ICI-bound axes: "
                f"{ICI_AXES}); put it in the ici tier")
    dcn_sizes = {a: config.dcn.get(a, 1) for a in DCN_AXES}
    ici_sizes = dict(config.ici)
    axis_names = tuple([a for a in DCN_AXES if dcn_sizes[a] > 1]
                       + list(ici_sizes))
    if not axis_names:
        raise ValueError("hybrid mesh needs at least one axis of size > 1")
    dcn_shape = tuple(dcn_sizes[a] for a in axis_names if a in DCN_AXES)
    ici_shape = tuple(ici_sizes[a] for a in axis_names
                      if a not in DCN_AXES)
    if devices is None:
        devices = jax.devices()
    total = int(np.prod(dcn_shape, dtype=np.int64)
                * np.prod(ici_shape, dtype=np.int64))
    if total != len(devices):
        raise ValueError(
            f"mesh asks for {total} devices, have {len(devices)}")
    if process_count() > 1:
        from jax.experimental import mesh_utils

        mesh_devices = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices)
        # create_hybrid_device_mesh returns [*dcn, *ici]-shaped devices.
        return Mesh(mesh_devices, axis_names)
    arr = np.asarray(devices).reshape(dcn_shape + ici_shape)
    return Mesh(arr, axis_names)


def host_local_batch_slice(global_batch: int) -> Tuple[int, int]:
    """(start, size) of this host's slice of a globally-sharded batch —
    the per-host data-loading contract (each host feeds only its chips)."""
    n = process_count()
    if global_batch % n:
        raise ValueError(
            f"global batch {global_batch} not divisible by "
            f"{n} processes")
    per = global_batch // n
    return process_index() * per, per
