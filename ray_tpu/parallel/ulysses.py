"""Ulysses (all-to-all) sequence parallelism.

Absent from the reference (SURVEY.md §2.4). DeepSpeed-Ulysses recipe,
TPU-native: inputs arrive sequence-sharded over ``sp``; an ``all_to_all``
re-shards to head-sharded/sequence-full, attention runs locally with every
token visible, and a second ``all_to_all`` restores sequence sharding.
Two all-to-alls on ICI replace the ring's n-1 permutes — better when
head count ≥ axis size and the full sequence fits per-chip.

Call inside ``shard_map`` over the ``sp`` axis.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.ring_attention import reference_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
    attn_fn: Optional[Callable] = None,
) -> jax.Array:
    """q/k/v per-shard [B, H, S_local, D] (sequence-sharded) ->
    [B, H, S_local, D]. H must be divisible by the axis size."""
    n = lax.axis_size(axis_name)
    if attn_fn is None:
        attn_fn = lambda q, k, v: reference_attention(  # noqa: E731
            q, k, v, causal=causal, scale=scale)
    if n == 1:
        return attn_fn(q, k, v)
    B, H, S, D = q.shape
    if H % n:
        raise ValueError(f"heads {H} not divisible by {axis_name} size {n}")

    def seq_to_heads(x):
        # [B, H, S_local, D] -> [B, H/n, S_global, D]: scatter head groups
        # to their shard, gather the full sequence (shard order = token
        # order, so the concat restores the global sequence).
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        # inverse: [B, H/n, S_global, D] -> [B, H, S_local, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    oh = attn_fn(qh, kh, vh)
    return heads_to_seq(oh)
