"""Job submission (reference role: ray/job_submission — dashboard JobManager
running entrypoints as subprocess drivers with status/log streaming)."""

from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional


class JobStatus(str, Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


@dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: JobStatus
    start_time: float
    end_time: Optional[float] = None
    return_code: Optional[int] = None
    metadata: Dict[str, str] = field(default_factory=dict)


class JobSubmissionClient:
    """Local job manager: runs entrypoints as subprocess drivers with
    captured logs under the session dir."""

    def __init__(self, address: Optional[str] = None):
        self._jobs: Dict[str, JobInfo] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._logs_dir = os.path.join(
            os.path.expanduser("~"), ".cache", "ray_tpu", "jobs")
        os.makedirs(self._logs_dir, exist_ok=True)
        self._lock = threading.Lock()

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   submission_id: Optional[str] = None) -> str:
        job_id = submission_id or f"raytpu_job_{uuid.uuid4().hex[:10]}"
        env = dict(os.environ)
        if runtime_env and runtime_env.get("env_vars"):
            env.update({k: str(v)
                        for k, v in runtime_env["env_vars"].items()})
        cwd = (runtime_env or {}).get("working_dir") or os.getcwd()
        log_path = os.path.join(self._logs_dir, f"{job_id}.log")
        log_f = open(log_path, "wb")
        proc = subprocess.Popen(
            entrypoint, shell=True, cwd=cwd, env=env,
            stdout=log_f, stderr=subprocess.STDOUT)
        info = JobInfo(job_id=job_id, entrypoint=entrypoint,
                       status=JobStatus.RUNNING, start_time=time.time(),
                       metadata=metadata or {})
        with self._lock:
            self._jobs[job_id] = info
            self._procs[job_id] = proc

        def reap():
            rc = proc.wait()
            log_f.close()
            with self._lock:
                info.end_time = time.time()
                info.return_code = rc
                if info.status != JobStatus.STOPPED:
                    info.status = (JobStatus.SUCCEEDED if rc == 0
                                   else JobStatus.FAILED)

        threading.Thread(target=reap, daemon=True,
                         name=f"job-reaper-{job_id}").start()
        return job_id

    def get_job_status(self, job_id: str) -> JobStatus:
        with self._lock:
            return self._jobs[job_id].status

    def get_job_info(self, job_id: str) -> JobInfo:
        with self._lock:
            return self._jobs[job_id]

    def get_job_logs(self, job_id: str) -> str:
        path = os.path.join(self._logs_dir, f"{job_id}.log")
        if not os.path.exists(path):
            return ""
        with open(path, "rb") as f:
            return f.read().decode(errors="replace")

    def list_jobs(self) -> List[JobInfo]:
        with self._lock:
            return list(self._jobs.values())

    def stop_job(self, job_id: str) -> bool:
        with self._lock:
            proc = self._procs.get(job_id)
            info = self._jobs.get(job_id)
        if proc is None or proc.poll() is not None:
            return False
        info.status = JobStatus.STOPPED
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
        return True

    def tail_job_logs(self, job_id: str):
        """Generator yielding log chunks until the job finishes."""
        path = os.path.join(self._logs_dir, f"{job_id}.log")
        pos = 0
        while True:
            if os.path.exists(path):
                with open(path, "rb") as f:
                    f.seek(pos)
                    chunk = f.read()
                    pos = f.tell()
                if chunk:
                    yield chunk.decode(errors="replace")
            status = self.get_job_status(job_id)
            if status in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                          JobStatus.STOPPED):
                break
            time.sleep(0.2)
