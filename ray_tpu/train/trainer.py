"""JaxTrainer: worker-group training with failure recovery (reference role:
ray/train TorchTrainer + BackendExecutor + WorkerGroup).

N worker actors run ``train_loop_per_worker``; each gets a session
(rank/world size/dataset shard), joins a collective group for out-of-program
sync (in-program collectives ride the Mesh), streams ``report()`` metrics,
and the trainer restarts the whole group from the latest checkpoint up to
``FailureConfig.max_failures`` times — the reference's group-restart
semantics.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu import collective
from ray_tpu._private.log import get_logger

log = get_logger(__name__)
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.session import TrainContext, _set_context


class TrainingFailedError(RuntimeError):
    pass


@dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    error: Optional[BaseException] = None
    path: Optional[str] = None


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable[..., None],
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self._loop = train_loop_per_worker
        self._loop_config = train_loop_config or {}
        self._scaling = scaling_config or ScalingConfig()
        self._run_config = run_config or RunConfig()
        self._datasets = datasets or {}
        self._restore_from: Optional[Checkpoint] = None
        self._ckpt_store = None  # lazy CheckpointStore over storage_path

    # ------------------------------------------------------------------ fit
    def fit(self) -> Result:
        ray_tpu.init(ignore_reinit_error=True)
        self._save_trainer_state()
        failures_allowed = self._run_config.failure_config.max_failures
        latest_ckpt: Optional[Checkpoint] = self._restore_from
        history: List[Dict[str, Any]] = []
        attempt = 0
        result = None
        while result is None:
            try:
                metrics, ckpt, hist = self._run_attempt(latest_ckpt)
                history.extend(hist)
                result = Result(metrics=metrics, checkpoint=ckpt,
                                metrics_history=history,
                                path=self._storage_dir())
            except Exception as exc:  # noqa: BLE001 — group failure boundary
                attempt += 1
                # Carry forward any checkpoint reported before the failure.
                latest_ckpt = getattr(exc, "_latest_checkpoint",
                                      latest_ckpt)
                if attempt > failures_allowed:
                    raise TrainingFailedError(
                        f"training failed after {attempt - 1} restart(s): "
                        f"{exc!r}") from exc
        # Drain any background checkpoint uploads before declaring the
        # run complete (async_save keeps them off the step loop).
        if self._ckpt_store is not None:
            try:
                self._ckpt_store.wait(timeout=120)
            except Exception:  # noqa: BLE001 — upload failure is IO, not
                pass  # training; the local checkpoint remains valid
            # Retention runs AFTER uploads land so async_save honors
            # num_to_keep too (per-persist pruning covers the sync path).
            keep = self._run_config.checkpoint_config.num_to_keep
            if keep:
                try:
                    for stale in \
                            self._ckpt_store.list_checkpoints()[:-keep]:
                        self._ckpt_store.delete(stale)
                except Exception:  # noqa: BLE001 — best-effort retention
                    pass
        # Callbacks close OUTSIDE the retry boundary: a logger bug must
        # not discard a completed training run (per-record on_result
        # already streamed live from _run_attempt's drain loop).
        for cb in self._run_config.callbacks:
            try:
                cb.on_end(result)
            except Exception:  # noqa: BLE001 — logger bug, not training
                pass
        return result

    def _storage_dir(self) -> Optional[str]:
        rc = self._run_config
        if rc.storage_path is None:
            return None
        if "://" in rc.storage_path:  # remote storage URI
            return f"{rc.storage_path.rstrip('/')}/{rc.name or 'train_run'}"
        d = os.path.join(rc.storage_path, rc.name or "train_run")
        os.makedirs(d, exist_ok=True)
        return d

    def _store(self):
        """Lazy CheckpointStore over the run's storage root (local dir
        or remote URI)."""
        if self._ckpt_store is None:
            root = self._storage_dir()
            if root is None:
                return None
            from ray_tpu.train.storage import CheckpointStore

            self._ckpt_store = CheckpointStore(root)
        return self._ckpt_store

    def _save_trainer_state(self):
        """Persist enough to rebuild this trainer (loop + configs) so
        ``JaxTrainer.restore(uri)`` works from storage alone (reference:
        trainer.pkl in the run directory)."""
        root = self._storage_dir()
        if root is None:
            return
        import cloudpickle

        from ray_tpu.data.filesystem import resolve_filesystem

        try:
            # Dump INSIDE the guard: an unpicklable loop must not fail
            # fit() — restore() then requires an explicit loop argument.
            state = cloudpickle.dumps({
                "loop": self._loop,
                "loop_config": self._loop_config,
                "scaling": self._scaling,
                "run_config": self._run_config,
            }, protocol=5)
            fs, p = resolve_filesystem(root)
            fs.makedirs(p)
            with fs.open(p.rstrip("/") + "/trainer.pkl", "wb") as f:
                f.write(state)
        except Exception:  # noqa: BLE001 — unpicklable loop / fs error
            pass

    # -------------------------------------------------------------- attempt
    def _run_attempt(self, restore_from: Optional[Checkpoint]):
        n = self._scaling.total_workers
        run_id = f"run-{id(self)}-{time.monotonic_ns()}"
        from ray_tpu.train.session import _group_name

        group_name = _group_name(run_id)

        # Shard datasets per worker (Dataset.split) once per attempt.
        shards_per_worker: List[Dict[str, Any]] = [dict() for _ in range(n)]
        for name, ds in self._datasets.items():
            if hasattr(ds, "split"):
                for rank, shard in enumerate(ds.split(n)):
                    shards_per_worker[rank][name] = shard
            else:
                for rank in range(n):
                    shards_per_worker[rank][name] = ds

        loop = self._loop
        loop_config = self._loop_config
        trial_name = self._run_config.name or "train"

        @ray_tpu.remote
        class TrainWorker:
            def run(self, rank):
                collective.init_collective_group(
                    n, rank, group_name=group_name)
                ctx = TrainContext(
                    world_rank=rank, world_size=n, run_id=run_id,
                    dataset_shards=shards_per_worker[rank],
                    latest_checkpoint=restore_from, trial_name=trial_name)
                _set_context(ctx)
                try:
                    if loop_config:
                        loop(loop_config)
                    else:
                        loop()
                finally:
                    _set_context(None)
                return rank

        # Cluster scaling: workers SPREAD across the driver + node
        # daemons (no-op standalone); resources_per_worker steers
        # feasibility — an infeasible-local demand forces every worker
        # onto the cluster (one per node when capacity divides that way).
        worker_opts: Dict[str, Any] = {"scheduling_strategy": "SPREAD"}
        if self._scaling.resources_per_worker:
            worker_opts["resources"] = dict(
                self._scaling.resources_per_worker)
        workers = [TrainWorker.options(**worker_opts).remote()
                   for _ in range(n)]
        run_refs = [w.run.remote(i) for i, w in enumerate(workers)]

        # Drain rank-0 reports from the KV channel while the group runs
        # (reference semantics: the trainer's result stream follows the
        # rank-0 worker; other ranks' reports are synchronization only).
        import pickle as _pickle

        from ray_tpu._private.worker import global_worker
        from ray_tpu.train.session import _report_key

        worker = global_worker()
        next_seq = [0] * n
        history: List[Dict[str, Any]] = []
        latest_metrics: Dict[str, Any] = {}
        latest_ckpt = restore_from

        def _drain():
            nonlocal latest_metrics, latest_ckpt
            for rank in range(n):
                while True:
                    raw = worker.kv_get(
                        _report_key(run_id, rank, next_seq[rank]))
                    if raw is None:
                        break
                    worker.kv_del(
                        _report_key(run_id, rank, next_seq[rank]))
                    next_seq[rank] += 1
                    if rank != 0:
                        continue  # non-rank-0 reports: consumed, discarded
                    metrics, ckpt = _pickle.loads(raw)
                    history.append(metrics)
                    latest_metrics = metrics
                    for cb in self._run_config.callbacks:
                        try:  # live stream; a logger bug must not fail
                            cb.on_result(metrics)  # the training group
                        except Exception as exc:
                            log.warning("train callback %r failed on a "
                                        "result: %r", cb, exc)
                    if ckpt is not None:
                        latest_ckpt = self._persist(ckpt)

        pending = list(run_refs)
        try:
            while pending:
                _drain()
                done, pending = ray_tpu.wait(
                    pending, num_returns=len(pending), timeout=0.05)
                if done:
                    ray_tpu.get(done)  # surface worker errors
        except Exception as exc:
            _drain()  # reports that raced with the failure carry the
            # checkpoint the restart must resume from
            exc._latest_checkpoint = latest_ckpt
            raise
        finally:
            _drain()  # reports that raced with completion
            collective.destroy_collective_group(group_name)
            for key in worker.kv_keys(f"train|{run_id}|".encode()):
                worker.kv_del(key)
            # Release the attempt's worker actors — process-backed actors
            # each hold an OS process + channel arenas until terminated.
            for w_handle in workers:
                try:
                    ray_tpu.kill(w_handle)
                except Exception:  # noqa: BLE001
                    pass
        return latest_metrics, latest_ckpt, history

    def _persist(self, ckpt: Checkpoint) -> Checkpoint:
        store = self._store()
        if store is None:
            return ckpt
        # Wall-clock, zero-padded: lexicographic order == creation order
        # even across process restarts (monotonic_ns resets per boot and
        # varies in digit count, which would mis-order restore()).
        name = f"checkpoint_{time.time_ns():020d}"
        cc = self._run_config.checkpoint_config
        if cc.async_save:
            # Upload off the drain loop; the LOCAL checkpoint stays
            # authoritative for restarts until the upload lands.
            store.persist_async(ckpt, name)
            out = ckpt
        else:
            dest = store.persist(ckpt, name)
            out = Checkpoint(dest) if not store.remote else ckpt
        keep = cc.num_to_keep
        if keep and not cc.async_save:
            for stale in store.list_checkpoints()[:-keep]:
                store.delete(stale)
        return out

    @staticmethod
    def restore(path: str, train_loop_per_worker=None,
                **overrides) -> "JaxTrainer":
        """Rebuild a trainer from its storage root (local dir or URI):
        the persisted trainer state supplies loop + configs (explicit
        arguments override), and training resumes from the LATEST stored
        checkpoint (reference: Trainer.restore(path))."""
        from ray_tpu.data.filesystem import resolve_filesystem
        from ray_tpu.train.storage import CheckpointStore

        state = {}
        try:
            fs, p = resolve_filesystem(path)
            with fs.open(p.rstrip("/") + "/trainer.pkl", "rb") as f:
                import cloudpickle

                state = cloudpickle.loads(f.read())
        except Exception:  # noqa: BLE001 — no persisted state
            if train_loop_per_worker is None:
                raise ValueError(
                    f"no trainer state at {path!r}; pass "
                    f"train_loop_per_worker explicitly") from None
        run_config = overrides.pop("run_config", None) \
            or state.get("run_config")
        if run_config is None:
            # No persisted state: derive storage from the restore path
            # itself so the resumed run KEEPS persisting checkpoints to
            # the root it was restored from.
            clean = path.rstrip("/")
            if "://" in clean:
                root, _, name = clean.rpartition("/")
            else:
                root, name = os.path.split(clean)
            run_config = RunConfig(name=name or None,
                                   storage_path=root or None)
        trainer = JaxTrainer(
            train_loop_per_worker or state.get("loop"),
            train_loop_config=overrides.pop(
                "train_loop_config", state.get("loop_config")),
            scaling_config=overrides.pop(
                "scaling_config", state.get("scaling")),
            run_config=run_config,
            **overrides,
        )
        # The storage root IS `path`; resume from its latest checkpoint.
        store = CheckpointStore(path)
        trainer._ckpt_store = None  # rebuilt lazily from run_config
        trainer._restore_from = store.latest()
        return trainer
