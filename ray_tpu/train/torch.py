"""Torch training utilities + TorchTrainer (reference role:
ray/train/torch — TorchTrainer, prepare_model, prepare_data_loader
[unverified]).

The reference wraps models in torch DDP over a NCCL/gloo process group.
Here data-parallel gradient averaging rides the SAME actor-plane
collective group every ray_tpu trainer uses (KV-rendezvous — works
across worker processes and real cluster nodes alike): prepare_model
attaches post-accumulate-grad hooks that, once every parameter's grad
is ready, run ONE fused allreduce over the flattened gradients. Torch
stays the user's programming model; the distributed plumbing is
ray_tpu's.
"""

from __future__ import annotations

from typing import Any, Optional

from ray_tpu.train.trainer import JaxTrainer


class TorchTrainer(JaxTrainer):
    """Reference-shaped entry point: ``train_loop_per_worker`` is a
    plain torch loop using ``prepare_model``/``prepare_data_loader``;
    scaling, failure recovery, checkpoints and reporting are the shared
    worker-group machinery (DataParallelTrainer parity)."""


def prepare_model(model):
    """DDP-equivalent: broadcast rank-0's initial parameters to every
    rank, then average gradients across the group after each backward
    pass. Returns the SAME module (hook-instrumented), so optimizers
    built on its parameters keep working."""
    import numpy as np
    import torch as _torch

    from ray_tpu import collective, train

    ctx = train.get_context()
    world = ctx.get_world_size()
    if world <= 1:
        return model
    group = ctx.collective_group

    # 1. Parameter sync: everyone adopts rank 0's init.
    with _torch.no_grad():
        flat = _torch.cat([p.detach().reshape(-1)
                           for p in model.parameters()])
        synced = collective.broadcast(flat.numpy(), src_rank=0,
                                      group_name=group)
        offset = 0
        for p in model.parameters():
            n = p.numel()
            p.copy_(_torch.from_numpy(
                np.asarray(synced[offset:offset + n])).reshape(p.shape))
            offset += n

    # 2. Gradient averaging: one fused allreduce per backward pass.
    # Completion is tracked PER BACKWARD PASS, not by counting hook
    # arrivals: the first hook to fire queues an autograd engine
    # callback that runs once the whole backward graph finishes. A
    # counter (len(params) arrivals) desyncs permanently the first time
    # any parameter receives no grad — frozen layer, unused branch,
    # conditional model path — and then fires mid-backward forever
    # after. The engine callback is immune: it runs exactly once per
    # backward regardless of how many hooked params participated
    # (params with no grad contribute zeros to the fused mean, matching
    # DDP's find_unused_parameters=True).
    #
    # Limitation (document-level parity with DDP): if a rank runs a
    # backward in which NO hooked parameter receives a grad, that rank
    # skips its allreduce while the others block in theirs — the same
    # hang torch DDP has without find_unused_parameters. Keep at least
    # one shared parameter on every backward path.
    params = [p for p in model.parameters() if p.requires_grad]
    state = {"queued": False}

    def _sync_all():
        with _torch.no_grad():
            grads = [(p.grad if p.grad is not None
                      else _torch.zeros_like(p)).reshape(-1)
                     for p in params]
            flat = _torch.cat(grads).numpy()
            mean = collective.allreduce(flat, group_name=group, op="mean")
            off = 0
            for p in params:
                n = p.numel()
                g = _torch.from_numpy(
                    np.asarray(mean[off:off + n])).reshape(p.shape)
                if p.grad is None:
                    p.grad = g
                else:
                    p.grad.copy_(g)
                off += n

    def _finalize():
        # Dedupe guard INSIDE the callback, not the hook: every hook
        # queues a callback, only the first to run syncs. A failed
        # backward (OOM, raising autograd Function) drops its queued
        # callbacks without running them — gating the QUEUEING on the
        # flag would then disable syncing forever; gating the SYNC
        # recovers on the next backward's fresh callbacks.
        if state["queued"]:
            state["queued"] = False
            _sync_all()

    def _hook(_param):
        state["queued"] = True
        _torch.autograd.Variable._execution_engine.queue_callback(
            _finalize)

    for p in params:
        p.register_post_accumulate_grad_hook(_hook)
    model._ray_tpu_sync_gradients = _sync_all  # manual escape hatch
    return model


def prepare_data_loader(loader):
    """Shard a DataLoader across the worker group: rebuilds it with a
    rank-aware DistributedSampler (no torch.distributed init needed —
    replicas/rank are passed explicitly)."""
    import torch.utils.data as tud

    from ray_tpu import train

    ctx = train.get_context()
    world = ctx.get_world_size()
    if world <= 1:
        return loader
    sampler = tud.distributed.DistributedSampler(
        loader.dataset, num_replicas=world,
        rank=ctx.get_world_rank(), shuffle=False)
    return tud.DataLoader(
        loader.dataset, batch_size=loader.batch_size, sampler=sampler,
        num_workers=0, collate_fn=loader.collate_fn,
        drop_last=loader.drop_last)
