"""Checkpoint storage backends (reference role:
ray/train/_internal/storage.py StorageContext — local/S3/GCS checkpoint
persistence [unverified]).

A CheckpointStore moves checkpoint directories between the local
filesystem and a storage URI through the Data filesystem registry
(local paths, ``memory://`` in tests, any fsspec scheme in production).
``persist_async`` uploads off the caller's thread so a training step
loop never blocks on checkpoint IO; ``wait()`` drains pending uploads
(called by the trainer once, outside the step loop).
"""

from __future__ import annotations

import os
import tempfile
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import List, Optional

from ray_tpu.data.filesystem import resolve_filesystem
from ray_tpu.train.checkpoint import Checkpoint


def _is_uri(path: str) -> bool:
    return "://" in path


def upload_dir(local_dir: str, dest_uri: str) -> str:
    """Copy a local directory tree to a storage URI (flat re-rooted
    file copies — works on object-store-shaped filesystems)."""
    fs, dest = resolve_filesystem(dest_uri)
    fs.makedirs(dest)
    dest = dest.rstrip("/")
    for root, _, files in os.walk(local_dir):
        rel = os.path.relpath(root, local_dir)
        for f in files:
            key = f"{dest}/{f}" if rel == "." else \
                f"{dest}/{rel.replace(os.sep, '/')}/{f}"
            fs.makedirs(key.rsplit("/", 1)[0])
            with open(os.path.join(root, f), "rb") as src, \
                    fs.open(key, "wb") as out:
                import shutil

                shutil.copyfileobj(src, out)  # streamed, not slurped
    return dest_uri


def download_dir(src_uri: str, local_dir: Optional[str] = None) -> str:
    """Fetch a storage URI's tree into a local directory."""
    fs, src = resolve_filesystem(src_uri)
    src = src.rstrip("/")
    local_dir = local_dir or tempfile.mkdtemp(prefix="ray_tpu_ckpt_dl_")
    files = fs.listdir(src)
    if not files:
        raise FileNotFoundError(f"no checkpoint files under {src_uri}")
    for key in files:
        rel = key[len(src):].lstrip("/")
        target = os.path.join(local_dir, *rel.split("/"))
        os.makedirs(os.path.dirname(target), exist_ok=True)
        with fs.open(key, "rb") as inp, open(target, "wb") as out:
            import shutil

            shutil.copyfileobj(inp, out)
    return local_dir


class CheckpointStore:
    """Persist checkpoints under one storage root (URI or local dir)."""

    def __init__(self, root: str):
        self.root = root.rstrip("/")
        self.remote = _is_uri(self.root)
        if not self.remote:
            os.makedirs(self.root, exist_ok=True)
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ckpt-upload")
        self._pending: List[Future] = []
        self._lock = threading.Lock()

    # ---------------------------------------------------------------- write
    def persist(self, ckpt: Checkpoint, name: str) -> str:
        """Synchronous persist; returns the checkpoint's URI/path."""
        dest = f"{self.root}/{name}"
        if self.remote:
            upload_dir(ckpt.as_directory(), dest)
        else:
            ckpt.copy_to(dest)
        return dest

    def persist_async(self, ckpt: Checkpoint, name: str) -> Future:
        """Persist on the upload thread; the caller (a training step
        loop) continues immediately. The returned future resolves to
        the destination URI."""
        fut = self._pool.submit(self.persist, ckpt, name)
        with self._lock:
            self._pending = [f for f in self._pending if not f.done()]
            self._pending.append(fut)
        return fut

    def wait(self, timeout: Optional[float] = None) -> List[str]:
        """Drain pending async persists; returns their URIs."""
        with self._lock:
            pending, self._pending = self._pending, []
        return [f.result(timeout=timeout) for f in pending]

    # ----------------------------------------------------------------- read
    def list_checkpoints(self) -> List[str]:
        """Checkpoint URIs under the root, lexicographically sorted
        (names embed a monotonic stamp, so the last is the latest)."""
        if not self.remote:
            if not os.path.isdir(self.root):
                return []
            return [f"{self.root}/{d}"
                    for d in sorted(os.listdir(self.root))
                    if d.startswith("checkpoint_")]
        fs, p = resolve_filesystem(self.root)
        names = set()
        prefix = p.rstrip("/") + "/"
        for key in fs.listdir(p):
            rel = key[len(prefix):]
            head = rel.split("/", 1)[0]
            if head.startswith("checkpoint_"):
                names.add(head)
        return [f"{self.root}/{n}" for n in sorted(names)]

    def fetch(self, uri: str) -> Checkpoint:
        """Materialize a stored checkpoint locally."""
        if not _is_uri(uri):
            return Checkpoint(uri)
        return Checkpoint(download_dir(uri))

    def latest(self) -> Optional[Checkpoint]:
        entries = self.list_checkpoints()
        return self.fetch(entries[-1]) if entries else None

    def delete(self, uri: str) -> None:
        if not _is_uri(uri):
            import shutil

            shutil.rmtree(uri, ignore_errors=True)
            return
        fs, p = resolve_filesystem(uri)
        # Object-store shaped: best-effort per-key removal when the
        # backing fs supports deletion.
        if hasattr(fs, "delete"):
            for k in fs.listdir(p):
                fs.delete(k)
