"""Result-logging callbacks (reference role: the AIR integration
callbacks — wandb/mlflow/comet loggers and tune's LoggerCallback base
[unverified]). Third-party trackers aren't available in this image, so
the shipped callbacks write local JSONL/CSV; the base class is the
extension point a wandb-style integration would subclass.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, List, Optional


class Callback:
    """Lifecycle hooks invoked by JaxTrainer.fit (and anything else that
    produces a result stream)."""

    def on_result(self, metrics: Dict[str, Any]) -> None:  # per report
        pass

    def on_end(self, result) -> None:  # final Result
        pass


class JsonLoggerCallback(Callback):
    """Appends one JSON line per reported result to ``<dir>/result.json``
    (the reference's result.json contract)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self.path = os.path.join(log_dir, "result.json")

    def on_result(self, metrics: Dict[str, Any]) -> None:
        with open(self.path, "a") as f:
            f.write(json.dumps(metrics, default=str) + "\n")


class CSVLoggerCallback(Callback):
    """Appends reported results to ``<dir>/progress.csv``, widening the
    header union-of-keys style like the reference's CSV logger."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self.path = os.path.join(log_dir, "progress.csv")
        self._fields: Optional[List[str]] = None

    def on_result(self, metrics: Dict[str, Any]) -> None:
        if self._fields is None:
            self._fields = sorted(metrics)
            with open(self.path, "w", newline="") as f:
                csv.DictWriter(f, fieldnames=self._fields).writeheader()
        with open(self.path, "a", newline="") as f:
            csv.DictWriter(f, fieldnames=self._fields,
                           extrasaction="ignore").writerow(
                {k: metrics.get(k) for k in self._fields})
