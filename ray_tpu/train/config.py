"""Train/AIR config dataclasses (reference role: ray/air/config.py)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False  # reference: use_gpu
    resources_per_worker: Optional[Dict[str, float]] = None

    @property
    def total_workers(self) -> int:
        return max(int(self.num_workers), 1)


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0  # restarts of the whole worker group


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_frequency: int = 0
    # Persist checkpoints on a background upload thread so the trainer's
    # report-drain loop (and therefore the training step cadence) never
    # blocks on storage IO; drained once at fit() end.
    async_save: bool = False


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig)
    # Result-stream hooks (train/callbacks.py) — the AIR integrations row.
    callbacks: list = dataclasses.field(default_factory=list)
