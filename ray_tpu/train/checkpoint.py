"""Checkpoints: directory + pytree persistence (reference role:
ray/train/_checkpoint.py + StorageContext).

A Checkpoint is a directory. Pytrees save via orbax when available
(async-capable sharded arrays — the TPU-native path), falling back to a
numpy .npz flat-tree encoding.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
from typing import Any, Dict, Optional


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    # ------------------------------------------------------------- creation
    @staticmethod
    def from_directory(path: str) -> "Checkpoint":
        return Checkpoint(path)

    @staticmethod
    def from_dict(data: Dict[str, Any]) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        with open(os.path.join(d, "data.pkl"), "wb") as f:
            pickle.dump(data, f)
        return Checkpoint(d)

    @staticmethod
    def from_pytree(tree: Any, path: Optional[str] = None) -> "Checkpoint":
        d = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(d, exist_ok=True)
        save_pytree(tree, os.path.join(d, "pytree"))
        return Checkpoint(d)

    # ------------------------------------------------------------ accessors
    def as_directory(self) -> str:
        return self.path

    def to_dict(self) -> Dict[str, Any]:
        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return pickle.load(f)

    def to_pytree(self) -> Any:
        return load_pytree(os.path.join(self.path, "pytree"))

    def copy_to(self, dest: str) -> "Checkpoint":
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return Checkpoint(dest)

    # ----------------------------------------------------------- URI plane
    def to_uri(self, uri: str) -> str:
        """Upload this checkpoint to a storage URI (memory://, any
        fsspec scheme) through the filesystem registry."""
        from ray_tpu.train.storage import upload_dir

        return upload_dir(self.path, uri)

    @staticmethod
    def from_uri(uri: str) -> "Checkpoint":
        """Materialize a stored checkpoint locally from its URI."""
        from ray_tpu.train.storage import download_dir

        return Checkpoint(download_dir(uri))

    def __repr__(self):
        return f"Checkpoint({self.path})"


def save_pytree(tree: Any, path: str) -> None:
    try:
        import orbax.checkpoint as ocp

        path = os.path.abspath(path)
        if os.path.exists(path):
            shutil.rmtree(path)
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(path, tree)
        return
    except Exception:  # noqa: BLE001 — orbax optional/strict; use fallback
        pass
    import jax
    import numpy as np

    os.makedirs(path, exist_ok=True)
    leaves, treedef = jax.tree.flatten(tree)
    np.savez(os.path.join(path, "leaves.npz"),
             **{str(i): np.asarray(x) for i, x in enumerate(leaves)})
    with open(os.path.join(path, "treedef.pkl"), "wb") as f:
        pickle.dump(treedef, f)


def load_pytree(path: str) -> Any:
    flat_file = os.path.join(path, "leaves.npz")
    if os.path.exists(flat_file):
        import jax
        import numpy as np

        data = np.load(flat_file)
        leaves = [data[str(i)] for i in range(len(data.files))]
        with open(os.path.join(path, "treedef.pkl"), "rb") as f:
            treedef = pickle.load(f)
        return jax.tree.unflatten(treedef, leaves)
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        return ckptr.restore(os.path.abspath(path))
