"""HuggingFace transformers trainer (reference role: the "other
trainers" family — ray/train/huggingface TransformersTrainer
[unverified]).

TPU-first shape: the per-worker loop fine-tunes a **Flax** transformers
model with one jitted optax train step (loss + grad + update fused by
XLA); data-parallel workers average gradients through the actor-plane
collective group the JaxTrainer already forms, so `ScalingConfig(
num_workers=N)` is N-way DP with no torch process group. Models come
from a ``model_init`` callable (config-constructed models work fully
offline; `from_pretrained` works wherever weights are local).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import JaxTrainer


def _default_loss(logits, labels):
    import jax.numpy as jnp
    import optax

    if logits.ndim == labels.ndim:  # regression
        return jnp.mean((logits - labels) ** 2)
    return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
        logits, labels))


def _make_transformers_loop(model_init: Callable[[], Any],
                            optimizer, loss_fn, num_epochs: int,
                            batch_size: int, report_every: int):
    def loop(config: Optional[Dict[str, Any]] = None):
        config = config or {}
        import jax
        import numpy as np
        import optax

        from ray_tpu import train
        from ray_tpu.collective import collective

        ctx_world = train.get_context().get_world_size()
        rank = train.get_context().get_world_rank()
        model = model_init()
        params = model.params
        opt = optimizer or optax.adamw(config.get("lr", 5e-5))
        opt_state = opt.init(params)
        lf = loss_fn or _default_loss

        @jax.jit
        def local_grads(params, batch):
            labels = batch["labels"]
            inputs = {k: v for k, v in batch.items() if k != "labels"}

            def closs(p):
                logits = model(**inputs, params=p).logits
                return lf(logits, labels)

            return jax.value_and_grad(closs)(params)

        @jax.jit
        def apply(params, opt_state, grads):
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        ds = train.get_dataset_shard("train")
        group = train.get_context().collective_group

        def batches():
            for _ in range(num_epochs):
                for b in ds.iter_batches(batch_size=batch_size):
                    yield {k: np.asarray(v) for k, v in b.items()}

        it = batches()
        if ctx_world > 1:
            # Ranks must agree on the step count or the per-step
            # allreduce deadlocks on uneven shards: take the group MIN of
            # local batch counts (standard DP drop-tail semantics).
            local_steps = sum(1 for _ in batches())
            n_steps = int(collective.allreduce(
                np.asarray(local_steps), group_name=group, op="min"))
        else:
            n_steps = None  # exhaust the iterator

        step_idx = 0
        last_loss = float("nan")
        for batch in it:
            if n_steps is not None and step_idx >= n_steps:
                break
            loss, grads = local_grads(params, batch)
            if ctx_world > 1:
                # DP gradient averaging (the torch-DDP role): ONE fused
                # allreduce per step — flatten every leaf into a single
                # f32 vector, reduce, then split back. Per-leaf rounds
                # would pay a KV-channel round trip per parameter.
                leaves, treedef = jax.tree_util.tree_flatten(grads)
                sizes = [int(np.asarray(g).size) for g in leaves]
                flat = np.concatenate(
                    [np.asarray(g, dtype=np.float32).ravel()
                     for g in leaves])
                summed = collective.allreduce(flat, group_name=group)
                parts = np.split(summed, np.cumsum(sizes)[:-1])
                grads = jax.tree_util.tree_unflatten(treedef, [
                    (p / ctx_world).reshape(np.shape(g)).astype(
                        np.asarray(g).dtype)
                    for p, g in zip(parts, leaves)])
            params, opt_state = apply(params, opt_state, grads)
            last_loss = float(loss)
            step_idx += 1
            if step_idx % report_every == 0:
                train.report({"loss": last_loss, "step": step_idx,
                              "rank": rank})
        train.report({"loss": last_loss, "step": step_idx, "rank": rank,
                      "done": True})

    return loop


class TransformersTrainer(JaxTrainer):
    """Fine-tune a Flax transformers model over dataset shards.

    ``datasets={"train": ds}`` must yield batches containing the model's
    input arrays plus ``labels``.
    """

    def __init__(self, *, model_init: Callable[[], Any],
                 optimizer=None,
                 loss_fn: Optional[Callable] = None,
                 num_epochs: int = 1,
                 batch_size: int = 8,
                 report_every: int = 10,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None):
        super().__init__(
            _make_transformers_loop(model_init, optimizer, loss_fn,
                                    num_epochs, batch_size, report_every),
            train_loop_config=train_loop_config or {},
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets)
