"""ray_tpu.train: distributed training orchestration.

Reference role: python/ray/train (TorchTrainer/BackendExecutor/WorkerGroup/
session/Checkpoint/FailureConfig). TPU-first deltas: the flagship trainer
is JaxTrainer; "process group setup" is a Mesh + collective group (no TCP
rendezvous — in-program collectives ride ICI); checkpoints are orbax-style
sharded pytrees in a directory.
"""

from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.callbacks import (
    Callback,
    CSVLoggerCallback,
    JsonLoggerCallback,
)
from ray_tpu.train.huggingface import TransformersTrainer
from ray_tpu.train.session import (
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train.storage import CheckpointStore
from ray_tpu.train import torch  # noqa: F401 — ray_tpu.train.torch.*
from ray_tpu.train.torch import TorchTrainer
from ray_tpu.train.trainer import JaxTrainer, Result, TrainingFailedError

# Reference-name alias: users arriving from the reference find the same
# entry point name wired to the jax path.
DataParallelTrainer = JaxTrainer

__all__ = [
    "CSVLoggerCallback",
    "Callback",
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointStore",
    "DataParallelTrainer",
    "JsonLoggerCallback",
    "TransformersTrainer",
    "FailureConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TorchTrainer",
    "TrainingFailedError",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "report",
]
