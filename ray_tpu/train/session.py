"""Per-worker training session (reference role: ray/train/_internal/session).

Thread-local context carrying rank/world_size/dataset shard; ``report()``
streams metrics (+ optional checkpoint) back to the trainer through the
driver's internal KV under ``(run_id, rank, seq)`` keys — the same
store-based channel the collective library uses, so it works identically
for in-driver and process-isolated training workers (whose KV calls ride
the per-worker API channel).
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

_local = threading.local()


def _report_key(run_id: str, rank: int, seq: int) -> bytes:
    return f"train|{run_id}|{rank}|{seq}".encode()


class TrainContext:
    def __init__(self, world_rank: int, world_size: int, run_id: str = "",
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 latest_checkpoint: Optional[Checkpoint] = None,
                 trial_name: str = ""):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = world_rank
        self.trial_name = trial_name
        self.run_id = run_id
        self._report_seq = 0
        self._dataset_shards = dataset_shards or {}
        self._latest_checkpoint = latest_checkpoint

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_trial_name(self) -> str:
        return self.trial_name

    @property
    def collective_group(self) -> str:
        """The worker group's actor-plane collective group name (joined
        by every worker before the loop runs)."""
        return _group_name(self.run_id)



def _group_name(run_id: str) -> str:
    """THE definition of a run's collective group name — trainer and
    session must agree or DP collectives join a group nobody set up."""
    return f"train-{run_id}"


def _set_context(ctx: Optional[TrainContext]):
    _local.ctx = ctx


def get_context() -> TrainContext:
    ctx = getattr(_local, "ctx", None)
    if ctx is None:
        raise RuntimeError(
            "no training session active (call inside train_loop_per_worker)")
    return ctx


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    from ray_tpu._private.worker import auto_init

    ctx = get_context()
    seq = ctx._report_seq
    ctx._report_seq = seq + 1
    auto_init().kv_put(
        _report_key(ctx.run_id, ctx.world_rank, seq),
        pickle.dumps((dict(metrics), checkpoint), protocol=5))


def get_checkpoint() -> Optional[Checkpoint]:
    return get_context()._latest_checkpoint


def get_dataset_shard(name: str = "train"):
    return get_context()._dataset_shards.get(name)
