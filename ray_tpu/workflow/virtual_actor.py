"""Durable virtual actors (reference role:
python/ray/workflow/virtual_actor_class.py [unverified]).

A virtual actor is a named, storage-backed stateful object: its state
snapshots ride the same ``WorkflowStorage`` commit protocol workflow
steps use, so the actor survives driver/node/head crashes —
``get_or_create`` in a fresh process rehydrates the last committed
snapshot. Method calls execute in the hosting process and commit a new
snapshot before returning; a crash mid-call loses at most that call
(its snapshot never committed), never prior state.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Union

from ray_tpu.workflow.storage import WorkflowStorage


class VirtualActorClass:
    """The ``@workflow.virtual_actor`` wrapper around a plain class."""

    def __init__(self, cls: type):
        if not isinstance(cls, type):
            raise TypeError(
                f"@workflow.virtual_actor target must be a class: {cls}")
        self._cls = cls

    def get_or_create(self, actor_id: str, *args,
                      storage: Optional[Union[str, WorkflowStorage]] = None,
                      **kwargs) -> "VirtualActorHandle":
        """Rehydrate the actor from its last committed snapshot, or
        construct it fresh (committing snapshot #0) when none exists."""
        from ray_tpu.workflow.api import _ensure_storage

        store = _ensure_storage(storage)
        loaded = store.load_actor_state(actor_id)
        obj = self._cls.__new__(self._cls)
        if loaded is not None:
            state, seq = loaded
            _set_state(obj, state)
        else:
            obj.__init__(*args, **kwargs)
            seq = 0
            if not store.save_actor_state(actor_id, _get_state(obj), seq):
                # A concurrent creator committed snapshot #0 first:
                # adopt its state instead of forking history.
                state, seq = store.load_actor_state(actor_id)
                obj = self._cls.__new__(self._cls)
                _set_state(obj, state)
        return VirtualActorHandle(actor_id, obj, seq, store)


def _get_state(obj) -> Any:
    if hasattr(obj, "__getstate__"):
        try:
            return obj.__getstate__()
        except TypeError:
            pass
    return dict(obj.__dict__)


def _set_state(obj, state) -> None:
    if hasattr(obj, "__setstate__"):
        obj.__setstate__(state)
    else:
        obj.__dict__.update(state)


class VirtualActorHandle:
    """Live handle to a virtual actor in THIS process. Method access
    returns a ``.run()``-able wrapper; each run commits a snapshot."""

    def __init__(self, actor_id: str, obj: Any, seq: int,
                 storage: WorkflowStorage):
        self._actor_id = actor_id
        self._obj = obj
        self._seq = seq
        self._storage = storage
        self._lock = threading.Lock()

    @property
    def actor_id(self) -> str:
        return self._actor_id

    def get_state(self) -> Dict[str, Any]:
        return _get_state(self._obj)

    def __getattr__(self, item: str):
        if item.startswith("_"):
            raise AttributeError(item)
        if not callable(getattr(type(self._obj), item, None)):
            raise AttributeError(
                f"virtual actor {type(self._obj).__name__!r} has no "
                f"method {item!r}")
        return _VirtualActorMethod(self, item)

    def __repr__(self):
        return (f"VirtualActorHandle({type(self._obj).__name__}, "
                f"id={self._actor_id!r}, seq={self._seq})")


class _VirtualActorMethod:
    def __init__(self, handle: VirtualActorHandle, method: str):
        self._handle = handle
        self._method = method

    def run(self, *args, **kwargs):
        h = self._handle
        with h._lock:
            result = getattr(h._obj, self._method)(*args, **kwargs)
            # Commit AFTER the method: a crash before this line replays
            # the call against the previous snapshot on the next
            # get_or_create — at-least-once for the in-flight call,
            # exactly-once for everything already committed. The commit
            # is a per-seq compare-and-set: losing it means ANOTHER
            # process advanced this actor — surface loudly instead of
            # silently dropping either writer's update.
            if not h._storage.save_actor_state(
                    h._actor_id, _get_state(h._obj), h._seq + 1):
                raise RuntimeError(
                    f"virtual actor {h._actor_id!r}: a concurrent "
                    f"writer committed seq {h._seq + 1} first — this "
                    f"handle is stale; get_or_create a fresh one and "
                    f"retry the call")
            h._seq += 1
            return result

    # Reference-parity aliases.
    def run_async(self, *args, **kwargs):
        import concurrent.futures

        pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        fut = pool.submit(self.run, *args, **kwargs)
        pool.shutdown(wait=False)
        return fut


def virtual_actor(cls: type) -> VirtualActorClass:
    """``@workflow.virtual_actor`` class decorator."""
    return VirtualActorClass(cls)
