"""ray_tpu.workflow — durable, crash-resumable workflows (reference
role: python/ray/workflow — the only SURVEY §1 L11 library the repo
lacked).

A workflow is a DAG of ``@workflow.step`` functions executed through
the normal task plane, with every step's output committed to a
``WorkflowStorage`` (local dir, ``memory://`` over the head KV, any
fsspec URI) before dependents run. Kill -9 the driver — or the head —
mid-run, and ``workflow.resume(workflow_id)`` replays the journal,
skips committed steps (exactly-once via idempotency tokens checked at
commit), and re-executes only the frontier. ``resume_all()`` sweeps
every interrupted workflow after a reattach. Durable virtual actors
snapshot named stateful objects through the same storage.

    from ray_tpu import workflow

    @workflow.step
    def fetch(): ...
    @workflow.step(max_retries=3, backoff_s=0.5)
    def train(data): ...

    dag = train.bind(fetch.bind())
    workflow.run(dag, workflow_id="nightly", storage="/data/workflows")
    # after a crash, from any process:
    workflow.resume("nightly", storage="/data/workflows")
"""

from ray_tpu.workflow.api import (
    FAILED,
    RUNNING,
    SUCCESS,
    StepNode,
    WorkflowStepFunction,
    delete,
    get_metadata,
    get_output,
    get_status,
    init,
    list_all,
    resume,
    resume_all,
    run,
    run_async,
    step,
)
from ray_tpu.workflow.storage import WorkflowStorage
from ray_tpu.workflow.virtual_actor import (
    VirtualActorClass,
    VirtualActorHandle,
    virtual_actor,
)

__all__ = [
    "FAILED", "RUNNING", "SUCCESS", "StepNode", "VirtualActorClass",
    "VirtualActorHandle", "WorkflowStepFunction", "WorkflowStorage",
    "delete", "get_metadata", "get_output", "get_status", "init",
    "list_all", "resume", "resume_all", "run", "run_async", "step",
    "virtual_actor",
]
