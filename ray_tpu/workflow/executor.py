"""Workflow executor: drives a step DAG over the normal task plane with
journal-checked, exactly-once step commits (reference role:
python/ray/workflow/workflow_executor.py + step_executor.py
[unverified]).

Execution walks the DAG in deterministic topological order. For each
step the journal is consulted FIRST: a committed step never re-executes
— its stored output stands in (loaded lazily: a resume over a 200-step
journal of committed steps touches only the outputs the frontier
actually consumes, so resume latency scales with the frontier, not the
history). Uncommitted steps submit through ``ray_tpu``'s scheduler /
worker plane as ordinary tasks — upstream outputs pass as ObjectRefs
(no re-serialization between live steps) — and their results commit
durably before any dependent runs.

Failure policy is per step: ``max_retries`` re-executions filtered by
``retry_exceptions`` with exponential ``backoff_s``, then either
``catch_exceptions`` (the committed output becomes a
``(result, error)`` continuation pair) or workflow failure.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Tuple

from ray_tpu.dag.dag_node import DAGNode, InputNode, MultiOutputNode
from ray_tpu.workflow.api import StepNode
from ray_tpu.workflow.storage import (
    FAILED,
    SUCCESS,
    WorkflowStorage,
)

_BACKOFF_CAP_S = 30.0


def step_ids_for(dag: DAGNode) -> List[Tuple[str, DAGNode]]:
    """Deterministic ``(step_id, node)`` assignment.

    Ids derive from the node's position in the DAG's topological order
    plus its step name. ``topological_order`` is a deterministic
    structural walk, and the DAG is persisted at first run — so a
    resume in a fresh process (unpickling the same structure) assigns
    the SAME ids and the journal lines up.
    """
    out = []
    for idx, node in enumerate(dag.topological_order()):
        if isinstance(node, InputNode):
            raise TypeError(
                "workflows are self-contained: InputNode is not allowed "
                "in a workflow DAG — bind concrete arguments instead")
        if isinstance(node, StepNode):
            out.append((f"{idx:04d}_{node.step_name}", node))
        elif isinstance(node, MultiOutputNode):
            out.append((f"{idx:04d}_multi_output", node))
        else:
            raise TypeError(
                f"workflow DAGs are built from @workflow.step functions; "
                f"got {type(node).__name__} — wrap the function with "
                f"@workflow.step")
    return out


class _Committed:
    """Lazy stand-in for a committed step's stored output."""

    __slots__ = ("step_id",)

    def __init__(self, step_id: str):
        self.step_id = step_id


class WorkflowExecutor:
    def __init__(self, storage: WorkflowStorage, workflow_id: str):
        self.storage = storage
        self.workflow_id = workflow_id
        self.steps_executed = 0
        self.steps_skipped = 0

    # ------------------------------------------------------------ helpers
    def _materialize(self, cache: Dict[int, Any], node: DAGNode):
        """Turn a cached upstream entry into something a task plane can
        consume: committed placeholders load from storage exactly when
        first needed and are put into the object store once."""
        val = cache[id(node)]
        if isinstance(val, _Committed):
            import ray_tpu

            loaded = self.storage.load_step_output(
                self.workflow_id, val.step_id)
            val = ray_tpu.put(loaded)
            cache[id(node)] = val  # one load per resumed consumer set
        return val

    def _resolve_args(self, cache: Dict[int, Any], node: DAGNode):
        args = tuple(
            self._materialize(cache, a) if isinstance(a, DAGNode) else a
            for a in node._bound_args)
        kwargs = {
            k: self._materialize(cache, v) if isinstance(v, DAGNode) else v
            for k, v in node._bound_kwargs.items()}
        return args, kwargs

    @staticmethod
    def _retryable(exc: BaseException, retry_exceptions) -> bool:
        if retry_exceptions is True:
            return isinstance(exc, Exception)
        if not retry_exceptions:
            return False
        return isinstance(exc, tuple(retry_exceptions) if isinstance(
            retry_exceptions, (list, tuple)) else retry_exceptions)

    def _run_step(self, step_id: str, node: StepNode,
                  cache: Dict[int, Any]) -> Any:
        """Execute one step through the task plane with the step's
        retry/backoff/catch policy; returns the VALUE to commit."""
        import ray_tpu
        from ray_tpu.remote_function import RemoteFunction

        opts = node._step_options
        task_opts: Dict[str, Any] = {
            "name": f"workflow:{self.workflow_id}:{step_id}",
            # The executor owns retries (durable attempt accounting +
            # backoff); the scheduler must not retry underneath it.
            "max_retries": 0,
        }
        for k in ("num_cpus", "num_tpus", "num_gpus", "resources"):
            if opts.get(k) is not None:
                task_opts[k] = opts[k]
        from ray_tpu._private import tracing

        fn = RemoteFunction(node._fn, task_opts)
        args, kwargs = self._resolve_args(cache, node)
        max_retries = int(opts.get("max_retries", 0) or 0)
        retry_exceptions = opts.get("retry_exceptions", True)
        backoff_s = float(opts.get("backoff_s", 0.1) or 0.0)
        attempts = 0
        while True:
            attempts += 1
            try:
                # Workflow-step entry point: one span per attempt; the
                # submitted task inherits it as the ambient context.
                with tracing.start_span(
                        "workflow.step", workflow=self.workflow_id,
                        step=step_id, attempt=attempts):
                    value = ray_tpu.get(fn.remote(*args, **kwargs))
                self._last_attempts = attempts
                if opts.get("catch_exceptions"):
                    return (value, None)
                return value
            except Exception as exc:  # noqa: BLE001 — step boundary
                if attempts <= max_retries and \
                        self._retryable(exc, retry_exceptions):
                    if backoff_s > 0:
                        time.sleep(min(
                            backoff_s * (2 ** (attempts - 1)),
                            _BACKOFF_CAP_S))
                    continue
                self._last_attempts = attempts
                if opts.get("catch_exceptions"):
                    # Commit the ORIGINAL exception, not the task-error
                    # wrapper: the wrapper's dynamically-derived type
                    # does not survive pickling, the cause does.
                    cause = getattr(exc, "cause", None)
                    return (None, cause if isinstance(
                        cause, BaseException) else exc)
                raise

    # ------------------------------------------------------------ execute
    def execute(self, dag: DAGNode) -> Any:
        assigned = step_ids_for(dag)
        cache: Dict[int, Any] = {}
        try:
            for step_id, node in assigned:
                if self.storage.step_commit_record(
                        self.workflow_id, step_id) is not None:
                    cache[id(node)] = _Committed(step_id)
                    self.steps_skipped += 1
                    continue
                if isinstance(node, MultiOutputNode):
                    value = [
                        self._value_of(cache, a)
                        for a in node._bound_args]
                    self._last_attempts = 1
                    t0 = time.monotonic()
                else:
                    t0 = time.monotonic()
                    value = self._run_step(step_id, node, cache)
                won, marker = self.storage.commit_step(
                    self.workflow_id, step_id, value, meta={
                        "attempts": self._last_attempts,
                        "duration_s": round(time.monotonic() - t0, 6),
                        "name": getattr(node, "step_name",
                                        "multi_output"),
                    })
                if not won:
                    # A racing resume committed first: its output is the
                    # canonical one (exactly-once) — adopt it.
                    cache[id(node)] = _Committed(step_id)
                else:
                    import ray_tpu

                    cache[id(node)] = ray_tpu.put(value)
                self.steps_executed += 1
        except Exception as exc:
            self.storage.set_status(self.workflow_id, FAILED,
                                    error=repr(exc))
            raise
        final = self._value_of(cache, dag)
        self.storage.save_result(self.workflow_id, final)
        self.storage.set_status(self.workflow_id, SUCCESS)
        return final

    def _value_of(self, cache: Dict[int, Any], node: DAGNode) -> Any:
        """A node's concrete VALUE (committed output loaded, live ref
        resolved)."""
        val = cache[id(node)]
        if isinstance(val, _Committed):
            return self.storage.load_step_output(
                self.workflow_id, val.step_id)
        import ray_tpu
        from ray_tpu._private.worker import ObjectRef

        if isinstance(val, ObjectRef):
            return ray_tpu.get(val)
        return val
