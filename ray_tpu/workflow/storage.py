"""Durable workflow storage + journal (reference role:
python/ray/workflow/workflow_storage.py + storage backends [unverified]).

A ``WorkflowStorage`` persists everything a workflow needs to survive a
driver, node, or head crash under one root — a local directory or any
URI the Data filesystem registry resolves (``memory://`` rides the head
KV and therefore the head's append-log; s3/gs via fsspec). Layout::

    <root>/<workflow_id>/dag.pkl                      # the step DAG
    <root>/<workflow_id>/meta.json                    # status record
    <root>/<workflow_id>/result.pkl                   # final output
    <root>/<workflow_id>/steps/<step_id>/output.<token>.pkl
    <root>/<workflow_id>/steps/<step_id>/commit.json  # the commit marker
    <root>/virtual_actors/<actor_id>/state.<token>.pkl + latest.json

Exactly-once is the commit protocol: a step's output is written under a
fresh idempotency token, then ``commit.json`` naming that token is
written LAST and read back. A step is committed iff its marker parses;
concurrent committers (two resumes racing) each write their own token
file and the marker read-back names the single winner every reader
follows — no committed output is ever clobbered or re-executed.

Workflow-level status is additionally journaled through the cluster KV
(``wfj|<id>`` keys) when a runtime is attached: the head's append-log
persists the journal across head restarts, so ``resume_all()`` on a
fresh driver (or a reattached head) can discover interrupted workflows
without scanning storage roots.
"""

from __future__ import annotations

import json
import pickle
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.data.filesystem import resolve_filesystem

# Workflow status lifecycle (journaled + stored in meta.json).
RUNNING = "RUNNING"
SUCCESS = "SUCCESS"
FAILED = "FAILED"

JOURNAL_PREFIX = b"wfj|"


def _dumps(value: Any) -> bytes:
    try:
        import cloudpickle

        return cloudpickle.dumps(value)
    except ImportError:
        return pickle.dumps(value)


def _loads(data: bytes) -> Any:
    return pickle.loads(data)


def _kv_worker():
    """The live runtime's KV surface (cluster-global when head-attached),
    or None when no runtime is up — storage then stands alone."""
    try:
        from ray_tpu._private.worker import try_live_worker

        return try_live_worker()
    except Exception:  # noqa: BLE001 — interpreter teardown
        return None


class WorkflowStorage:
    """One storage root's workflow persistence surface."""

    def __init__(self, root: str):
        self.root = root.rstrip("/")
        self._fs, self._base = resolve_filesystem(self.root)
        self._base = self._base.rstrip("/")
        if not getattr(self._fs, "atomic_put_if_absent", False):
            # Exactly-once rests on exclusive marker creation. Backends
            # without an atomic create (generic fsspec: s3/gs) degrade
            # to best-effort single-winner with a stale-read race
            # window between concurrent resumes — say so loudly once.
            import warnings

            warnings.warn(
                f"workflow storage {self.root!r}: backend has no atomic "
                f"exclusive-create; exactly-once step commits degrade "
                f"to best-effort when multiple resumes race (a single "
                f"resumer is unaffected)", RuntimeWarning,
                stacklevel=3)

    # ------------------------------------------------------------ raw IO
    def _key(self, rel: str) -> str:
        return f"{self._base}/{rel}"

    def _write(self, rel: str, data: bytes) -> None:
        key = self._key(rel)
        parent = key.rsplit("/", 1)[0]
        self._fs.makedirs(parent)
        with self._fs.open(key, "wb") as f:
            f.write(data)

    def _read(self, rel: str) -> Optional[bytes]:
        try:
            with self._fs.open(self._key(rel), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None
        except OSError:
            return None

    def _exists(self, rel: str) -> bool:
        return self._fs.exists(self._key(rel))

    def _write_if_absent(self, rel: str, data: bytes) -> bool:
        key = self._key(rel)
        parent = key.rsplit("/", 1)[0]
        self._fs.makedirs(parent)
        return self._fs.put_if_absent(key, data)

    def _read_json(self, rel: str) -> Optional[dict]:
        raw = self._read(rel)
        if raw is None:
            return None
        try:
            return json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            return None  # torn write (crash mid-commit): not committed

    # ------------------------------------------------------- workflow meta
    def save_dag(self, workflow_id: str, dag: Any) -> None:
        self._write(f"{workflow_id}/dag.pkl", _dumps(dag))

    def load_dag(self, workflow_id: str) -> Any:
        raw = self._read(f"{workflow_id}/dag.pkl")
        if raw is None:
            raise ValueError(
                f"workflow {workflow_id!r} has no persisted DAG under "
                f"{self.root!r} — was it ever run against this storage?")
        return _loads(raw)

    def set_status(self, workflow_id: str, status: str,
                   error: Optional[str] = None) -> None:
        """Write the status record to storage AND the KV journal. Storage
        is the durable source of truth for resume; the journal makes
        interrupted workflows discoverable cluster-wide."""
        rec = {
            "workflow_id": workflow_id,
            "status": status,
            "root": self.root,
            "updated_at": time.time(),
        }
        if error is not None:
            rec["error"] = error
        self._write(f"{workflow_id}/meta.json",
                    json.dumps(rec).encode())
        w = _kv_worker()
        if w is not None:
            try:
                w.kv_put(JOURNAL_PREFIX + workflow_id.encode(),
                         json.dumps(rec).encode())
            except Exception:  # noqa: BLE001 — journal is best-effort
                pass

    def get_status(self, workflow_id: str) -> Optional[dict]:
        rec = self._read_json(f"{workflow_id}/meta.json")
        if rec is not None:
            return rec
        # Fall back to the journal (covers a crash between journal write
        # and meta write — the windows are adjacent but distinct). Only
        # a record journaled for THIS root counts: the same workflow_id
        # under a different root is a different workflow.
        w = _kv_worker()
        if w is not None:
            try:
                raw = w.kv_get(JOURNAL_PREFIX + workflow_id.encode())
                if raw is not None:
                    rec = json.loads(raw.decode())
                    if rec.get("root") == self.root:
                        return rec
            except Exception:  # noqa: BLE001
                pass
        return None

    def list_workflows(self) -> List[dict]:
        """Status records for every workflow visible from this root:
        the storage scan unioned with KV-journal entries for this root."""
        by_id: Dict[str, dict] = {}
        try:
            # Immediate children only: shallow os.scandir on local
            # roots, delimiter ls() on fsspec — never a recursive walk
            # over step-output files. memory:// stays one prefix key
            # scan (a flat KV has no cheaper listing).
            seen_ids = {c for c in self._fs.children(self._base)
                        if c and c != "virtual_actors"}
        except (OSError, ValueError):
            seen_ids = set()
        for wid in sorted(seen_ids):
            rec = self._read_json(f"{wid}/meta.json")
            by_id[wid] = rec or {"workflow_id": wid, "status": RUNNING,
                                 "root": self.root}
        w = _kv_worker()
        if w is not None:
            try:
                for key in w.kv_keys(JOURNAL_PREFIX):
                    raw = w.kv_get(key)
                    if raw is None:
                        continue
                    rec = json.loads(raw.decode())
                    if rec.get("root") == self.root:
                        by_id.setdefault(rec["workflow_id"], rec)
            except Exception:  # noqa: BLE001 — journal is best-effort
                pass
        return [by_id[k] for k in sorted(by_id)]

    def delete_workflow(self, workflow_id: str) -> None:
        self._delete_tree(f"{workflow_id}")
        w = _kv_worker()
        if w is not None:
            try:
                w.kv_del(JOURNAL_PREFIX + workflow_id.encode())
            except Exception:  # noqa: BLE001
                pass

    def _delete_tree(self, rel: str) -> None:
        base = self._key(rel)
        if hasattr(self._fs, "delete"):
            try:
                for key in self._fs.listdir(base):
                    self._fs.delete(key)
            except (OSError, ValueError):
                pass
        if "://" not in base:
            # Local roots: also remove the now-empty directory tree.
            import shutil

            shutil.rmtree(base, ignore_errors=True)

    # ------------------------------------------------------- step commits
    def step_commit_record(self, workflow_id: str,
                           step_id: str) -> Optional[dict]:
        """The commit marker, or None when the step has not durably
        committed (absent or torn marker — either way it re-executes)."""
        rec = self._read_json(f"{workflow_id}/steps/{step_id}/commit.json")
        if rec is None or "token" not in rec:
            return None
        return rec

    def commit_step(self, workflow_id: str, step_id: str, value: Any,
                    meta: Optional[dict] = None) -> Tuple[bool, dict]:
        """Durably commit a step output, exactly-once.

        Returns ``(won, marker)``: ``won`` is False when another
        committer's marker already names a different token — the caller
        must treat the stored output (the winner's) as canonical and
        discard its own result.
        """
        existing = self.step_commit_record(workflow_id, step_id)
        if existing is not None:
            return False, existing
        token = uuid.uuid4().hex
        base = f"{workflow_id}/steps/{step_id}"
        self._write(f"{base}/output.{token}.pkl", _dumps(value))
        marker = dict(meta or {})
        marker["token"] = token
        marker["committed_at"] = time.time()
        # Idempotency check AT commit: the marker is created with
        # EXCLUSIVE semantics (O_EXCL locally, overwrite=False on the
        # KV-backed memory fs) — of N racing committers exactly one
        # wins; losers adopt the winner's token and discard their own
        # output. No committed output is ever clobbered.
        won = self._write_if_absent(
            f"{base}/commit.json", json.dumps(marker).encode())
        final = self.step_commit_record(workflow_id, step_id)
        if final is None:  # storage refused the marker: surface loudly
            raise IOError(
                f"commit marker for {workflow_id}/{step_id} unreadable "
                f"immediately after write")
        return won and final.get("token") == token, final

    def load_step_output(self, workflow_id: str, step_id: str) -> Any:
        rec = self.step_commit_record(workflow_id, step_id)
        if rec is None:
            raise ValueError(
                f"step {step_id!r} of workflow {workflow_id!r} has no "
                f"committed output")
        raw = self._read(
            f"{workflow_id}/steps/{step_id}/output.{rec['token']}.pkl")
        if raw is None:
            raise IOError(
                f"step {step_id!r} marker names token {rec['token']} but "
                f"its output file is missing")
        return _loads(raw)

    # ------------------------------------------------------- final result
    def save_result(self, workflow_id: str, value: Any) -> None:
        self._write(f"{workflow_id}/result.pkl", _dumps(value))

    def load_result(self, workflow_id: str) -> Any:
        raw = self._read(f"{workflow_id}/result.pkl")
        if raw is None:
            raise ValueError(
                f"workflow {workflow_id!r} has no stored result")
        return _loads(raw)

    def has_result(self, workflow_id: str) -> bool:
        return self._exists(f"{workflow_id}/result.pkl")

    # ----------------------------------------------------- virtual actors
    # Superseded snapshots are pruned down to this many trailing seqs
    # after each successful commit — only the highest committed seq is
    # ever read, so an actor's footprint stays bounded no matter how
    # many calls it serves.
    ACTOR_KEEP_SNAPSHOTS = 3

    def save_actor_state(self, actor_id: str, state: Any,
                         seq: int) -> bool:
        """Commit snapshot number `seq` with the same exclusive-marker
        protocol steps use: one ``commit.<seq>.json`` per sequence
        number, created if-absent. Returns False when a CONCURRENT
        writer already committed this seq (optimistic concurrency —
        the caller lost the race and must reload)."""
        token = uuid.uuid4().hex
        base = f"virtual_actors/{actor_id}"
        self._write(f"{base}/state.{token}.pkl", _dumps(state))
        won = self._write_if_absent(
            f"{base}/commit.{seq:08d}.json", json.dumps(
                {"token": token, "seq": seq,
                 "committed_at": time.time()}).encode())
        if won:
            try:
                self._prune_actor_snapshots(actor_id, seq)
            except Exception:  # noqa: BLE001 — GC is best-effort
                pass
        return won

    def _prune_actor_snapshots(self, actor_id: str, latest_seq: int
                               ) -> None:
        """Delete markers (and their state files) more than
        ACTOR_KEEP_SNAPSHOTS behind the just-committed seq."""
        if not hasattr(self._fs, "delete"):
            return
        base = f"virtual_actors/{actor_id}"
        cutoff = latest_seq - self.ACTOR_KEEP_SNAPSHOTS
        if cutoff < 0:
            return
        for key in self._fs.listdir(self._key(base)):
            name = key.rsplit("/", 1)[-1]
            if not (name.startswith("commit.") and name.endswith(".json")):
                continue
            try:
                seq = int(name[len("commit."):-len(".json")])
            except ValueError:
                continue
            if seq >= cutoff:
                continue
            rec = self._read_json(f"{base}/{name}")
            self._fs.delete(key)
            if rec and "token" in rec:
                self._fs.delete(
                    self._key(f"{base}/state.{rec['token']}.pkl"))

    def load_actor_state(self, actor_id: str) -> Optional[Tuple[Any, int]]:
        """The HIGHEST committed snapshot (markers are write-once per
        seq, so the max marker is the canonical latest state)."""
        base = f"virtual_actors/{actor_id}"
        try:
            keys = self._fs.listdir(self._key(base))
        except (OSError, ValueError):
            return None
        markers = sorted(k for k in keys
                         if k.rsplit("/", 1)[-1].startswith("commit.")
                         and k.endswith(".json"))
        for key in reversed(markers):  # newest first; skip torn tails
            seq_txt = key.rsplit("/", 1)[-1][len("commit."):-len(".json")]
            rel = f"{base}/{key.rsplit('/', 1)[-1]}"
            rec = self._read_json(rel)
            if rec is None or "token" not in rec:
                continue
            raw = self._read(f"{base}/state.{rec['token']}.pkl")
            if raw is None:
                continue
            return _loads(raw), int(rec.get("seq", int(seq_txt)))
        return None

    def list_actors(self) -> List[str]:
        try:
            return sorted(
                self._fs.children(f"{self._base}/virtual_actors"))
        except (OSError, ValueError):
            return []
