"""Durable workflow API (reference role: python/ray/workflow/api.py —
``@workflow.step``, ``workflow.run/resume/resume_all``, introspection
[unverified]).

``@workflow.step`` wraps a function so ``.bind()`` (alias ``.step()``)
builds a lazy DAG node — the same authoring surface as ``ray_tpu.dag``,
with per-step durability options layered on. ``workflow.run(dag,
workflow_id=...)`` persists the DAG, then executes it step by step
through the normal task plane, committing each step's output to a
``WorkflowStorage`` before moving on. A crashed driver (or head) leaves
a journal behind; ``workflow.resume(workflow_id)`` replays it, skips
every committed step, and re-executes only the frontier.
"""

from __future__ import annotations

import functools
import os
import threading
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from ray_tpu.dag.dag_node import DAGNode
from ray_tpu.workflow.storage import (
    FAILED,
    RUNNING,
    SUCCESS,
    WorkflowStorage,
)

_STEP_OPTION_KEYS = frozenset({
    "name", "max_retries", "retry_exceptions", "backoff_s",
    "catch_exceptions", "num_cpus", "num_tpus", "num_gpus", "resources",
})

_global_storage: Optional[WorkflowStorage] = None
_storage_lock = threading.Lock()


def init(storage: Optional[Union[str, WorkflowStorage]] = None) -> None:
    """Set the process-global workflow storage root (a local directory
    or a ``scheme://`` URI). Called implicitly with the default root by
    the first run/resume that doesn't name one."""
    global _global_storage
    with _storage_lock:
        if storage is None or isinstance(storage, str):
            _global_storage = WorkflowStorage(storage or _default_root())
        else:
            _global_storage = storage


def _default_root() -> str:
    from ray_tpu._private.config import GlobalConfig

    return GlobalConfig.workflow_storage or os.path.join(
        os.path.expanduser("~"), ".ray_tpu", "workflows")


def _ensure_storage(
        storage: Optional[Union[str, WorkflowStorage]]) -> WorkflowStorage:
    if isinstance(storage, WorkflowStorage):
        return storage
    if isinstance(storage, str):
        return WorkflowStorage(storage)
    with _storage_lock:
        global _global_storage
        if _global_storage is None:
            _global_storage = WorkflowStorage(_default_root())
        return _global_storage


class StepNode(DAGNode):
    """A bound workflow step: a plain function + durability options.

    Deliberately NOT a FunctionNode — the executor owns submission so it
    can check the commit journal first; and the node must cloudpickle
    (the whole DAG is persisted at run()), so it carries the raw
    function, not a live RemoteFunction handle.
    """

    def __init__(self, fn: Callable, options: Dict[str, Any],
                 args: Tuple, kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._fn = fn
        self._step_options = dict(options)

    @property
    def step_name(self) -> str:
        return self._step_options.get("name") or getattr(
            self._fn, "__name__", "step")

    def _execute_one(self, cache, input_values):
        raise TypeError(
            "StepNode cannot execute outside a workflow; use "
            "workflow.run(dag, workflow_id=...)")


class WorkflowStepFunction:
    """The ``@workflow.step`` wrapper: ``.bind()`` builds DAG nodes,
    ``.options()`` layers per-step durability/resource options."""

    def __init__(self, fn: Callable, options: Dict[str, Any]):
        for k in options:
            if k not in _STEP_OPTION_KEYS:
                raise ValueError(f"unknown @workflow.step option {k!r}")
        self._fn = fn
        self._options = options
        functools.update_wrapper(self, fn)

    def options(self, **options) -> "WorkflowStepFunction":
        merged = dict(self._options)
        merged.update(options)
        return WorkflowStepFunction(self._fn, merged)

    def bind(self, *args, **kwargs) -> StepNode:
        return StepNode(self._fn, self._options, args, kwargs)

    # Classic reference spelling: ``f.step(...)`` == ``f.bind(...)``.
    step = bind

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"workflow step {self.__name__!r} cannot be called directly; "
            f"use {self.__name__}.bind() inside a workflow DAG.")


def step(fn: Optional[Callable] = None, **options):
    """``@workflow.step`` / ``@workflow.step(max_retries=3, ...)``.

    Options: ``name``, ``max_retries`` (re-executions on failure),
    ``retry_exceptions`` (True or an exception tuple to filter),
    ``backoff_s`` (base of the exponential retry backoff),
    ``catch_exceptions`` (step output becomes ``(result, None)`` /
    ``(None, exception)``), plus task resources
    (``num_cpus``/``num_tpus``/``resources``).
    """
    if fn is not None:
        if not callable(fn):
            raise TypeError(f"@workflow.step target must be callable: {fn}")
        return WorkflowStepFunction(fn, options)

    def _wrap(f):
        return WorkflowStepFunction(f, options)

    return _wrap


# ------------------------------------------------------------------ verbs
def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        storage: Optional[Union[str, WorkflowStorage]] = None) -> Any:
    """Execute a step DAG durably; returns the final step's output.

    Re-running a completed ``workflow_id`` returns the stored result
    without re-executing anything; re-running an interrupted one resumes
    it (committed steps skip — the same path ``resume`` takes).
    """
    from ray_tpu.workflow.executor import WorkflowExecutor

    store = _ensure_storage(storage)
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:12]}"
    rec = store.get_status(workflow_id)
    if rec is not None and rec.get("status") == SUCCESS \
            and store.has_result(workflow_id):
        return store.load_result(workflow_id)
    if not isinstance(dag, DAGNode):
        raise TypeError(
            f"workflow.run expects a DAG of workflow steps, got {dag!r}")
    # Persist the DAG FIRST: resume() must be able to rebuild the plan
    # from storage alone, with the authoring driver long dead.
    store.save_dag(workflow_id, dag)
    store.set_status(workflow_id, RUNNING)
    return WorkflowExecutor(store, workflow_id).execute(dag)


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              storage: Optional[Union[str, WorkflowStorage]] = None):
    """``run`` on a background thread; returns a
    ``concurrent.futures.Future`` resolving to the final output."""
    import concurrent.futures

    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:12]}"
    pool = concurrent.futures.ThreadPoolExecutor(
        max_workers=1, thread_name_prefix=f"workflow-{workflow_id[:8]}")
    fut = pool.submit(run, dag, workflow_id=workflow_id, storage=storage)
    pool.shutdown(wait=False)
    return fut


def resume(workflow_id: str,
           storage: Optional[Union[str, WorkflowStorage]] = None) -> Any:
    """Resume an interrupted workflow from its journal: the persisted
    DAG is replayed, committed steps load from storage (never
    re-execute), and only the frontier runs."""
    from ray_tpu.workflow.executor import WorkflowExecutor

    store = _ensure_storage(storage)
    rec = store.get_status(workflow_id)
    if rec is None:
        raise ValueError(
            f"no workflow {workflow_id!r} under {store.root!r}")
    if rec.get("status") == SUCCESS and store.has_result(workflow_id):
        return store.load_result(workflow_id)
    dag = store.load_dag(workflow_id)
    store.set_status(workflow_id, RUNNING)
    return WorkflowExecutor(store, workflow_id).execute(dag)


def resume_all(storage: Optional[Union[str, WorkflowStorage]] = None,
               include_failed: bool = False) -> Dict[str, Any]:
    """Resume every interrupted (status RUNNING — i.e. its driver died
    mid-run) workflow visible from the storage root / KV journal; the
    head-reattach recovery sweep. Returns ``{workflow_id: result}``;
    workflows that fail again record the exception object instead."""
    store = _ensure_storage(storage)
    results: Dict[str, Any] = {}
    wanted = {RUNNING} | ({FAILED} if include_failed else set())
    for rec in store.list_workflows():
        if rec.get("status") not in wanted:
            continue
        wid = rec["workflow_id"]
        try:
            results[wid] = resume(wid, storage=store)
        except Exception as exc:  # noqa: BLE001 — sweep must not abort
            results[wid] = exc
    return results


# -------------------------------------------------------- introspection
def get_status(workflow_id: str,
               storage: Optional[Union[str, WorkflowStorage]] = None
               ) -> Optional[str]:
    rec = _ensure_storage(storage).get_status(workflow_id)
    return rec.get("status") if rec else None


def get_metadata(workflow_id: str,
                 storage: Optional[Union[str, WorkflowStorage]] = None
                 ) -> dict:
    """The status record plus per-step commit markers (attempts,
    durations, tokens)."""
    store = _ensure_storage(storage)
    rec = store.get_status(workflow_id)
    if rec is None:
        raise ValueError(
            f"no workflow {workflow_id!r} under {store.root!r}")
    steps = {}
    try:
        dag = store.load_dag(workflow_id)
        from ray_tpu.workflow.executor import step_ids_for

        for sid, _node in step_ids_for(dag):
            steps[sid] = store.step_commit_record(workflow_id, sid)
    except ValueError:
        pass  # no DAG persisted (torn first write): meta alone
    return dict(rec, steps=steps)


def get_output(workflow_id: str,
               storage: Optional[Union[str, WorkflowStorage]] = None
               ) -> Any:
    """The stored final output of a completed workflow."""
    store = _ensure_storage(storage)
    if store.has_result(workflow_id):
        return store.load_result(workflow_id)
    rec = store.get_status(workflow_id)
    if rec is None:
        raise ValueError(
            f"no workflow {workflow_id!r} under {store.root!r}")
    raise RuntimeError(
        f"workflow {workflow_id!r} has no stored output (status "
        f"{rec.get('status')!r}); resume() it to completion first")


def list_all(status_filter: Optional[str] = None,
             storage: Optional[Union[str, WorkflowStorage]] = None
             ) -> List[Tuple[str, str]]:
    """``[(workflow_id, status)]`` for every workflow under the root."""
    out = []
    for rec in _ensure_storage(storage).list_workflows():
        st = rec.get("status", RUNNING)
        if status_filter is None or st == status_filter:
            out.append((rec["workflow_id"], st))
    return out


def delete(workflow_id: str,
           storage: Optional[Union[str, WorkflowStorage]] = None) -> None:
    _ensure_storage(storage).delete_workflow(workflow_id)


__all__ = [
    "FAILED", "RUNNING", "SUCCESS", "StepNode", "WorkflowStepFunction",
    "delete", "get_metadata", "get_output", "get_status", "init",
    "list_all", "resume", "resume_all", "run", "run_async", "step",
]
