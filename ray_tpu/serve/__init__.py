"""ray_tpu.serve: model serving (reference role: python/ray/serve).

Controller reconciles deployments to target replica counts; replicas are
actors; a Router picks replicas per request with power-of-two-choices on
queue length; DeploymentHandles compose deployments (async futures);
@serve.batch dynamically batches — the TPU-relevant feature, since batching
is what keeps the MXU fed at serving time. HTTP ingress is a thin stdlib
http.server proxy (the reference uses uvicorn; no new deps here); gRPC
ingress serves ANY `/<pkg.Service>/<Method>` through generic unary
handlers with no protoc step (`serve/grpc.py`).
"""

from ray_tpu.serve.api import (
    Application,
    Deployment,
    batch,
    delete,
    deploy_config,
    deployment,
    get_deployment_handle,
    ingress,
    multiplexed,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.handle import (
    DeploymentHandle,
    DeploymentResponse,
    DeploymentResponseGenerator,
)
from ray_tpu.serve.grpc import start_grpc_proxy, stop_grpc_proxy

__all__ = [
    "Application",
    "Deployment",
    "DeploymentHandle",
    "DeploymentResponse",
    "DeploymentResponseGenerator",
    "batch",
    "delete",
    "deploy_config",
    "deployment",
    "get_deployment_handle",
    "ingress",
    "multiplexed",
    "run",
    "shutdown",
    "start",
    "start_grpc_proxy",
    "status",
    "stop_grpc_proxy",
]
