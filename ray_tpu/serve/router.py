"""Replica scheduling (reference role: serve/_private/replica_scheduler/
pow_2_scheduler.py — power-of-two-choices on replica queue length)."""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional


class ReplicaSet:
    """Tracks live replica handles + their in-flight request counts."""

    def __init__(self):
        self._replicas: List[Any] = []
        # In-flight counts keyed by replica identity, not list index:
        # after update() replaces/removes replicas, index-keyed counts would
        # transfer to whichever replica now occupies that slot and skew the
        # power-of-two choice.
        self._inflight: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._rng = random.Random(0)

    def update(self, replicas: List[Any]):
        with self._lock:
            self._replicas = list(replicas)
            live = {id(r) for r in replicas}
            self._inflight = {
                k: v for k, v in self._inflight.items() if k in live
            }

    def size(self) -> int:
        with self._lock:
            return len(self._replicas)

    def choose(self) -> (int, Any):
        """Power of two choices: sample two replicas, pick the one with the
        shorter queue. Falls back to the single replica when size==1.

        Returns (key, replica); pass the key back to release()."""
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                raise RuntimeError("no replicas available")
            if n == 1:
                replica = self._replicas[0]
            else:
                a, b = self._rng.sample(range(n), 2)
                ra, rb = self._replicas[a], self._replicas[b]
                qa = self._inflight.get(id(ra), 0)
                qb = self._inflight.get(id(rb), 0)
                replica = ra if qa <= qb else rb
            key = id(replica)
            self._inflight[key] = self._inflight.get(key, 0) + 1
            return key, replica

    def release(self, key: int):
        with self._lock:
            if self._inflight.get(key, 0) > 0:
                self._inflight[key] -= 1

    def queue_lengths(self) -> List[int]:
        with self._lock:
            return [self._inflight.get(id(r), 0) for r in self._replicas]
