"""Replica scheduling (reference role: serve/_private/replica_scheduler/
pow_2_scheduler.py — power-of-two-choices on replica queue length, plus
a prefix-cache-aware tier for LLM deployments).

Prefix-aware routing: replicas that expose a prefix digest (the LLM
engine's registered block-chain hashes — see
``PagedKVCache.prefix_digest``) are scored by **cached-prefix overlap**
with the incoming prompt: the router chains the prompt's block digests
and counts how many LEADING blocks each replica already holds. The
best-overlap replica wins — a request landing there skips recomputing
the shared prefill entirely — unless it is drastically more loaded than
the least-loaded replica (the same resident-bytes-with-load-slack idiom
``remote_router._choose_node`` uses for data locality: locality wins,
but never into a hotspot). Requests with no overlap (or deployments
that never report digests) fall through to power-of-two-choices
untouched.
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.exceptions import RequestSheddedError

# A cached-prefix hit must cover at least this many tokens to override
# the load-balancing choice (one block is the minimum shareable unit).
PREFIX_MIN_OVERLAP_TOKENS = 1
# Max extra in-flight requests the overlap winner may carry vs the
# least-loaded replica before locality yields to load (the
# locality_load_slack idiom from the task router).
PREFIX_LOAD_SLACK = 2

# Priority-admission policy: class p may occupy up to
# fraction[min(p, last)] of the deployment's max_ongoing_requests, so
# as load builds the worst classes hit their (smaller) ceiling and shed
# first while class 0 still admits up to the full cap — nested
# thresholds, the standard priority-shedding shape.
DEFAULT_CLASS_FRACTIONS = (1.0, 0.75, 0.5, 0.25)


class ReplicaSet:
    """Tracks live replica handles + their in-flight request counts."""

    def __init__(self):
        self._replicas: List[Any] = []
        # In-flight counts keyed by replica identity, not list index:
        # after update() replaces/removes replicas, index-keyed counts would
        # transfer to whichever replica now occupies that slot and skew the
        # power-of-two choice.
        self._inflight: Dict[int, int] = {}
        # Prefix-cache reports keyed the same way: id(replica) ->
        # (block_size, frozenset of chain digests).
        self._prefix: Dict[int, Tuple[int, frozenset]] = {}
        self._lock = threading.Lock()
        self._rng = random.Random(0)
        # Priority admission (None = unlimited, the default): total
        # in-flight bound + per-class fractions of it.
        self._max_ongoing: Optional[int] = None
        self._class_fractions: Tuple[float, ...] = DEFAULT_CLASS_FRACTIONS
        # -- counters (tests/dashboards read these) --
        self.prefix_routed = 0          # requests routed by overlap
        self.prefix_overlap_tokens = 0  # cumulative overlap they carried
        self.shed_total = 0             # requests refused by admission
        self.shed_by_class: Dict[int, int] = {}
        self.admitted_by_class: Dict[int, int] = {}

    def update(self, replicas: List[Any]):
        with self._lock:
            self._replicas = list(replicas)
            live = {id(r) for r in replicas}
            self._inflight = {
                k: v for k, v in self._inflight.items() if k in live
            }
            self._prefix = {
                k: v for k, v in self._prefix.items() if k in live
            }

    def size(self) -> int:
        with self._lock:
            return len(self._replicas)

    # ------------------------------------------------- priority admission
    def configure_admission(self, max_ongoing: Optional[int],
                            class_fractions=None) -> None:
        """Bound total in-flight requests across the deployment's
        replicas. ``None`` disables admission control (default).
        ``class_fractions[p]`` scales the bound per priority class
        (class 0 = first entry = most important; classes past the end
        use the last entry)."""
        with self._lock:
            self._max_ongoing = (None if max_ongoing is None
                                 else max(1, int(max_ongoing)))
            if class_fractions is not None:
                self._class_fractions = tuple(
                    float(f) for f in class_fractions) or \
                    DEFAULT_CLASS_FRACTIONS

    def _admit_locked(self, priority: int) -> None:
        """Admission check for one request of class ``priority``; raises
        a typed ``RequestSheddedError`` when the class's nested
        threshold is full. Caller holds the lock and increments the
        in-flight count right after (shed requests never count)."""
        cap = self._max_ongoing
        if cap is None:
            self.admitted_by_class[priority] = \
                self.admitted_by_class.get(priority, 0) + 1
            return
        p = max(0, int(priority))
        frac = self._class_fractions[min(p, len(self._class_fractions) - 1)]
        limit = max(1, int(cap * frac))
        total = sum(self._inflight.values())
        if total >= limit:
            self.shed_total += 1
            self.shed_by_class[p] = self.shed_by_class.get(p, 0) + 1
            # Retry hint grows with how far past the class ceiling the
            # deployment is running — a crude queueing-delay estimate.
            retry = min(2.0, 0.1 * (1.0 + total / limit))
            raise RequestSheddedError(
                f"deployment at {total} ongoing requests >= class-{p} "
                f"admission limit {limit} (cap {cap}); shed by policy",
                priority=p, retry_after_s=retry)
        self.admitted_by_class[p] = self.admitted_by_class.get(p, 0) + 1

    def admission_stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "max_ongoing_requests": self._max_ongoing,
                "class_fractions": list(self._class_fractions),
                "ongoing": sum(self._inflight.values()),
                "shed_total": self.shed_total,
                "shed_by_class": dict(self.shed_by_class),
                "admitted_by_class": dict(self.admitted_by_class),
            }

    # ---------------------------------------------------------- prefix tier
    def update_prefix_digest(self, key: int, block_size: int,
                             digests) -> None:
        """Record one replica's cached-prefix report (the controller
        polls ``prefix_digest()`` off the request path)."""
        with self._lock:
            self._prefix[key] = (int(block_size), frozenset(digests))

    def has_prefix_digests(self) -> bool:
        with self._lock:
            return bool(self._prefix)

    def _prefix_candidate(self, digests_by_bs) -> Optional[Any]:
        """Best replica by contiguous leading-block overlap, or None
        when nothing (usefully) matches / the winner is overloaded.
        Caller holds the lock; the prompt digests were hashed OUTSIDE
        it (``digests_by_bs``: block_size -> chain digests)."""
        best, best_tokens = None, 0
        for r in self._replicas:
            ent = self._prefix.get(id(r))
            if ent is None:
                continue
            bs, dset = ent
            digs = digests_by_bs.get(bs)
            if digs is None:
                continue  # report arrived between snapshot and scoring
            overlap = 0
            for d in digs:
                if d not in dset:
                    break
                overlap += 1
            tokens = overlap * bs
            if tokens > best_tokens:
                best, best_tokens = r, tokens
        if best is None or best_tokens < PREFIX_MIN_OVERLAP_TOKENS:
            return None
        min_inflight = min(
            (self._inflight.get(id(r), 0) for r in self._replicas),
            default=0)
        if self._inflight.get(id(best), 0) > min_inflight + \
                PREFIX_LOAD_SLACK:
            return None  # cached replica is a hotspot: balance instead
        self.prefix_routed += 1
        self.prefix_overlap_tokens += best_tokens
        return best

    def plan_prefix(self, prefix_tokens) -> int:
        """Best cached-prefix overlap (in TOKENS) any replica advertises
        for this prompt — the disagg pairing layer's tail-skip plan: a
        prefill replica ships only blocks past this overlap, betting the
        prefix tier routes the decode stream onto the same winner.
        Advisory only: the decode replica re-validates against its OWN
        cache at graft time and a stale plan falls back, so over-
        estimating here costs a re-prefill, never correctness."""
        if prefix_tokens is None:
            return 0
        with self._lock:
            sizes = {bs for bs, _ in self._prefix.values()}
        if not sizes:
            return 0
        from ray_tpu.llm.kv_cache import chain_digests

        digests_by_bs = {bs: chain_digests(prefix_tokens, bs)
                         for bs in sizes}
        best = 0
        with self._lock:
            for r in self._replicas:
                ent = self._prefix.get(id(r))
                if ent is None:
                    continue
                bs, dset = ent
                digs = digests_by_bs.get(bs)
                if digs is None:
                    continue
                overlap = 0
                for d in digs:
                    if d not in dset:
                        break
                    overlap += 1
                best = max(best, overlap * bs)
        return best

    # -------------------------------------------------------------- choose
    def choose(self, prefix_tokens=None, priority: int = 0) -> (int, Any):
        """Prefix-overlap scoring when ``prefix_tokens`` is given and a
        replica reported digests; otherwise power of two choices: sample
        two replicas, pick the one with the shorter queue. Falls back to
        the single replica when size==1. When admission control is
        configured (``max_ongoing_requests``) the request is first
        admitted against its priority class's nested threshold — a shed
        raises ``RequestSheddedError`` without touching any replica.

        Returns (key, replica); pass the key back to release()."""
        # Hash the prompt OUTSIDE the lock (a 4k prompt is hundreds of
        # chained blake2b links — concurrent routing must not serialize
        # on it); only the cheap set-overlap scoring holds the lock.
        digests_by_bs = None
        if prefix_tokens is not None:
            with self._lock:
                sizes = {bs for bs, _ in self._prefix.values()}
            if sizes:
                from ray_tpu.llm.kv_cache import chain_digests

                digests_by_bs = {
                    bs: chain_digests(prefix_tokens, bs) for bs in sizes
                }
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                raise RuntimeError("no replicas available")
            self._admit_locked(priority)
            replica = None
            if digests_by_bs and n > 1 and self._prefix:
                replica = self._prefix_candidate(digests_by_bs)
            if replica is None:
                if n == 1:
                    replica = self._replicas[0]
                else:
                    a, b = self._rng.sample(range(n), 2)
                    ra, rb = self._replicas[a], self._replicas[b]
                    qa = self._inflight.get(id(ra), 0)
                    qb = self._inflight.get(id(rb), 0)
                    replica = ra if qa <= qb else rb
            key = id(replica)
            self._inflight[key] = self._inflight.get(key, 0) + 1
            return key, replica

    def release(self, key: int):
        with self._lock:
            if self._inflight.get(key, 0) > 0:
                self._inflight[key] -= 1

    def queue_lengths(self) -> List[int]:
        with self._lock:
            return [self._inflight.get(id(r), 0) for r in self._replicas]
