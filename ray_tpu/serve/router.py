"""Replica scheduling (reference role: serve/_private/replica_scheduler/
pow_2_scheduler.py — power-of-two-choices on replica queue length)."""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional


class ReplicaSet:
    """Tracks live replica handles + their in-flight request counts."""

    def __init__(self):
        self._replicas: List[Any] = []
        self._inflight: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._rng = random.Random(0)

    def update(self, replicas: List[Any]):
        with self._lock:
            self._replicas = list(replicas)
            self._inflight = {
                i: self._inflight.get(i, 0)
                for i in range(len(replicas))
            }

    def size(self) -> int:
        with self._lock:
            return len(self._replicas)

    def choose(self) -> (int, Any):
        """Power of two choices: sample two replicas, pick the one with the
        shorter queue. Falls back to the single replica when size==1."""
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                raise RuntimeError("no replicas available")
            if n == 1:
                idx = 0
            else:
                a, b = self._rng.sample(range(n), 2)
                idx = a if self._inflight[a] <= self._inflight[b] else b
            self._inflight[idx] += 1
            return idx, self._replicas[idx]

    def release(self, idx: int):
        with self._lock:
            if idx in self._inflight and self._inflight[idx] > 0:
                self._inflight[idx] -= 1

    def queue_lengths(self) -> List[int]:
        with self._lock:
            return [self._inflight[i] for i in range(len(self._replicas))]
