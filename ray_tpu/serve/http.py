"""HTTP ingress proxy (reference role: serve/_private/proxy.py — there a
uvicorn/gRPC server per node; here a stdlib ThreadingHTTPServer, zero new
dependencies).

POST/GET /<deployment> routes the JSON body to the deployment's handle via
the same pow-2 router as handle calls.
"""

from __future__ import annotations

import json
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ray_tpu.exceptions import RequestSheddedError
from ray_tpu.serve.controller import get_or_create_controller
from ray_tpu.serve.handle import DeploymentHandle


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # silence per-request stderr noise
        pass

    def _route(self):
        from ray_tpu._private import tracing

        if tracing._TRACER is None:
            self._route_inner()
            return
        # HTTP entry point: one trace per proxy request; the handle's
        # serve.request span (and everything below it) parents here —
        # look the request up afterwards via /api/traces.
        with tracing.start_span("http.request", path=self.path,
                                method=self.command):
            self._route_inner()

    def _route_inner(self):
        from urllib.parse import parse_qs, unquote, urlparse

        parsed = urlparse(self.path)
        segments = parsed.path.strip("/").split("/")
        name = segments[0]
        if not name:
            self.send_response(404)
            self.end_headers()
            self.wfile.write(b'{"error": "no deployment in path"}')
            return
        length = int(self.headers.get("Content-Length", 0) or 0)
        body = self.rfile.read(length) if length else b""
        controller = get_or_create_controller()
        if controller.is_ingress(name):
            # ASGI ingress: /<deployment>/<subpath> drives the bound app
            # with path=/<subpath> inside the replica.
            # ASGI-3: scope path is percent-DECODED; trailing slashes
            # are routing-significant and must survive.
            sub_path = "/" + "/".join(unquote(s) for s in segments[1:])
            if parsed.path.endswith("/") and sub_path != "/":
                sub_path += "/"
            request = {
                "method": self.command,
                "path": sub_path,
                "query_string": (parsed.query or "").encode(),
                "headers": list(self.headers.items()),
                "body": body,
            }
            try:
                handle = DeploymentHandle(name, controller)
                out = handle.options("__serve_asgi__").remote(
                    request).result(timeout=30)
                self.send_response(int(out.get("status", 200)))
                payload = out.get("body", b"")
                for k, v in out.get("headers", []):
                    if k.lower() not in ("content-length",
                                         "transfer-encoding"):
                        self.send_header(k, v)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
            except Exception as exc:  # noqa: BLE001 — request boundary
                payload = json.dumps({"error": repr(exc)}).encode()
                self.send_response(500)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
            return
        qs = parse_qs(parsed.query)
        stream = qs.get("stream", ["0"])[0] in ("1", "true")
        # Priority class for admission/shedding: the X-Request-Priority
        # header or ?priority= (0 = most important, the default).
        try:
            priority = int(self.headers.get(
                "X-Request-Priority", qs.get("priority", ["0"])[0]))
        except (TypeError, ValueError):
            priority = 0
        try:
            arg = json.loads(body) if body else None
            handle = DeploymentHandle(name, controller,
                                      priority=priority)
            if stream:
                # Chunked transfer: one JSON line per generator item, sent
                # as the replica yields (reference: streaming responses
                # over the proxy).
                gen = (handle.options(stream=True).remote(arg)
                       if arg is not None
                       else handle.options(stream=True).remote())
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    for item in gen:
                        chunk = (json.dumps(item) + "\n").encode()
                        self.wfile.write(
                            f"{len(chunk):X}\r\n".encode() + chunk
                            + b"\r\n")
                except Exception as exc:  # noqa: BLE001 — mid-stream error
                    # Headers are already on the wire: the error must ride
                    # the chunked framing (a 500 here would corrupt the
                    # stream), then the stream terminates cleanly.
                    chunk = (json.dumps({"error": repr(exc)})
                             + "\n").encode()
                    self.wfile.write(
                        f"{len(chunk):X}\r\n".encode() + chunk + b"\r\n")
                finally:
                    # Client disconnect / mid-stream error: cancel the
                    # replica's generator and release its in-flight slot
                    # (an abandoned proxy stream must not count as
                    # ongoing forever, nor keep generating tokens).
                    try:
                        gen.close()
                    except Exception:  # noqa: BLE001
                        pass
                self.wfile.write(b"0\r\n\r\n")
                return
            result = (handle.remote(arg) if arg is not None
                      else handle.remote()).result(timeout=30)
            payload = json.dumps({"result": result}).encode()
            self.send_response(200)
        except KeyError:
            payload = json.dumps({"error": f"no deployment {name!r}"}
                                 ).encode()
            self.send_response(404)
        except RequestSheddedError as exc:
            # Shed by the admission policy (choose() raises before any
            # replica is touched, so for streams too this lands before
            # headers went out): 503 + Retry-After — the client-visible
            # contract that overload is retryable policy, not failure.
            payload = json.dumps({
                "error": str(exc), "shed": True,
                "priority": exc.priority,
                "retry_after_s": exc.retry_after_s,
            }).encode()
            self.send_response(503)
            self.send_header("Retry-After",
                             str(max(1, math.ceil(exc.retry_after_s))))
        except Exception as exc:  # noqa: BLE001 — request error boundary
            payload = json.dumps({"error": repr(exc)}).encode()
            self.send_response(500)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    do_GET = _route
    do_POST = _route
    do_PUT = _route
    do_DELETE = _route
    do_PATCH = _route
    do_HEAD = _route
    do_OPTIONS = _route


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="serve-http-proxy")
        self._thread.start()

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()


_proxy: Optional[HTTPProxy] = None


def start_proxy(host: str = "127.0.0.1", port: int = 8000) -> HTTPProxy:
    global _proxy
    if _proxy is None:
        _proxy = HTTPProxy(host, port)
    return _proxy


def stop_proxy():
    global _proxy
    if _proxy is not None:
        _proxy.shutdown()
        _proxy = None
