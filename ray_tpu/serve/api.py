"""Public serve API (reference role: serve/api.py — @serve.deployment,
.bind() applications, serve.run, @serve.batch, @serve.multiplexed)."""

from __future__ import annotations

import asyncio
import functools
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.controller import (
    AutoscalingConfig,
    get_or_create_controller,
    shutdown_controller,
)
from ray_tpu.serve.handle import DeploymentHandle


class Application:
    """A bound deployment graph root (result of Deployment.bind)."""

    def __init__(self, deployment: "Deployment", args, kwargs):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


class Deployment:
    def __init__(self, cls_or_fn, name: str, num_replicas: int = 1,
                 autoscaling_config: Optional[dict] = None,
                 max_ongoing_requests: Optional[int] = None,
                 ray_actor_options: Optional[dict] = None,
                 **_opts):
        self._target = cls_or_fn
        self.name = name
        self.num_replicas = num_replicas
        self.autoscaling_config = autoscaling_config
        # Priority admission + load shedding: total in-flight bound
        # across the deployment's replicas (None = unlimited). Requests
        # past their priority class's nested threshold are refused with
        # a typed RequestSheddedError (HTTP: 503 + Retry-After).
        self.max_ongoing_requests = max_ongoing_requests
        # Per-replica actor options (reference: deployment
        # ray_actor_options — num_cpus/resources). A replica with a
        # real resource demand places like any actor: infeasible
        # demand parks as an unmet shape in the driver's heartbeat, so
        # a ClusterAutoscaler LAUNCHES a node for it — replica
        # scale-up drives real node scale-up.
        self.ray_actor_options = dict(ray_actor_options or {})

    def options(self, **opts) -> "Deployment":
        merged = dict(
            name=self.name, num_replicas=self.num_replicas,
            autoscaling_config=self.autoscaling_config,
            max_ongoing_requests=self.max_ongoing_requests,
            ray_actor_options=self.ray_actor_options)
        merged.update(opts)
        return Deployment(self._target, **merged)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)


def deployment(_cls=None, *, name: Optional[str] = None,
               num_replicas: int = 1,
               autoscaling_config: Optional[dict] = None,
               max_ongoing_requests: Optional[int] = None,
               ray_actor_options: Optional[dict] = None, **opts):
    """@serve.deployment decorator for classes or functions."""

    def wrap(cls):
        target = cls
        if not isinstance(cls, type):
            # Function deployment: wrap into a callable class.
            fn = cls

            class _FnDeployment:
                def __call__(self, *a, **k):
                    return fn(*a, **k)

            _FnDeployment.__name__ = getattr(fn, "__name__", "fn")
            target = _FnDeployment
        return Deployment(
            target, name or getattr(cls, "__name__", "deployment"),
            num_replicas=num_replicas,
            autoscaling_config=autoscaling_config,
            max_ongoing_requests=max_ongoing_requests,
            ray_actor_options=ray_actor_options, **opts)

    return wrap(_cls) if _cls is not None else wrap


def _deploy_app(app: Application) -> DeploymentHandle:
    """Deploy an application graph: bound handle args resolve depth-first
    (deployment composition — reference handle-passing semantics)."""
    controller = get_or_create_controller()

    def resolve(value):
        if isinstance(value, Application):
            return _deploy_app(value)
        return value

    args = tuple(resolve(a) for a in app.args)
    kwargs = {k: resolve(v) for k, v in app.kwargs.items()}
    d = app.deployment
    auto = None
    if d.autoscaling_config:
        auto = AutoscalingConfig(**d.autoscaling_config)
    controller.deploy(d.name, d._target, args, kwargs,
                      num_replicas=d.num_replicas, autoscaling=auto,
                      max_ongoing_requests=d.max_ongoing_requests,
                      ray_actor_options=d.ray_actor_options)
    return DeploymentHandle(d.name, controller)


def run(app: Application, *, name: str = "default", route_prefix: str = "/",
        blocking: bool = False) -> DeploymentHandle:
    ray_tpu.init(ignore_reinit_error=True)
    handle = _deploy_app(app)
    return handle


def deploy_config(config, *, start_http: bool = False) -> Dict[str, Any]:
    """Config-file deploy (reference role: `serve deploy config.yaml` —
    the declarative REST/config schema, subset): a dict, YAML, or JSON
    file with ``applications: [{import_path: "module:app", name: ...,
    deployments: [{name, num_replicas, autoscaling_config}]}]``.
    ``import_path`` resolves to an Application (or a Deployment, which is
    bound with no args); per-deployment overrides apply before deploy.
    Returns {app_name: handle}."""
    import importlib
    import json as _json

    if isinstance(config, dict):
        cfg = config
    else:
        with open(config) as f:
            text = f.read()
        try:
            import yaml

            cfg = yaml.safe_load(text)
        except ImportError:
            cfg = _json.loads(text)
    handles: Dict[str, Any] = {}
    for app_cfg in cfg.get("applications", []):
        mod_name, _, attr = app_cfg["import_path"].partition(":")
        target = getattr(importlib.import_module(mod_name), attr)
        app = target.bind() if isinstance(target, Deployment) else target
        if not isinstance(app, Application):
            raise TypeError(
                f"{app_cfg['import_path']} is not an Application or "
                f"Deployment")
        overrides = {d["name"]: d for d in app_cfg.get("deployments", [])}
        o = overrides.get(app.deployment.name)
        if o:
            opts = {k: v for k, v in o.items() if k != "name"}
            app = Application(app.deployment.options(**opts),
                              app.args, app.kwargs)
        handles[app_cfg.get("name", app.deployment.name)] = run(app)
    if start_http:
        from ray_tpu.serve.http import start_proxy

        http_cfg = cfg.get("http_options", {})
        start_proxy(host=http_cfg.get("host", "127.0.0.1"),
                    port=int(http_cfg.get("port", 8000)))
    return handles


def start(detached: bool = False, **_opts):
    ray_tpu.init(ignore_reinit_error=True)
    get_or_create_controller()


def status() -> Dict[str, Any]:
    return get_or_create_controller().status()


def delete(name: str):
    get_or_create_controller().delete(name)


def shutdown():
    shutdown_controller()


def get_deployment_handle(name: str, app_name: str = "default"
                          ) -> DeploymentHandle:
    return DeploymentHandle(name, get_or_create_controller())


def ingress(asgi_app=None):
    """ASGI ingress (reference role: serve's FastAPI ingress —
    ``@serve.ingress(app)``). Works with ANY ASGI-3 application (FastAPI,
    Starlette, or a plain callable); this image ships no ASGI framework,
    so the contract is the protocol itself. The decorator injects a
    ``__serve_asgi__`` replica method that drives the app for one HTTP
    request; the proxy routes ``/<deployment>/<subpath>`` through it with
    ``path=/<subpath>``."""

    def wrap(cls):
        cls.__serve_ingress__ = asgi_app

        def __serve_asgi__(self, request: dict) -> dict:
            app = type(self).__serve_ingress__
            if app is None:
                raise ValueError("no ASGI app bound to this deployment")
            runner = getattr(self, "_serve_asgi_runner", None)
            if runner is None:
                runner = _AsgiRunner(app)
                self._serve_asgi_runner = runner
            return runner.handle(request)

        cls.__serve_asgi__ = __serve_asgi__
        return cls

    return wrap


class _AsgiRunner:
    """Per-replica ASGI host: one persistent event loop thread for the
    app (not a fresh asyncio.run per request) with the lifespan protocol
    driven ONCE at startup — FastAPI/Starlette startup handlers (DB
    pools, model loads) run before the first request, as under uvicorn.
    Apps that do not speak lifespan are tolerated (the spec allows
    rejecting it)."""

    def __init__(self, app):
        import asyncio
        import queue as _queue

        self.app = app
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop_main, daemon=True, name="serve-asgi-loop")
        self._thread.start()

        self._lifespan_q: "_queue.Queue" = _queue.Queue()
        started = threading.Event()
        state: dict = {}

        async def lifespan():
            scope = {"type": "lifespan", "asgi": {"version": "3.0"},
                     "state": state}
            incoming = [{"type": "lifespan.startup"}]

            async def receive():
                if incoming:
                    return incoming.pop(0)
                # Block until shutdown (never, for replica lifetime).
                return await asyncio.get_event_loop().create_future()

            async def send(msg):
                if msg["type"] in ("lifespan.startup.complete",
                                   "lifespan.startup.failed"):
                    started.set()

            try:
                await self.app(scope, receive, send)
            except BaseException:  # noqa: BLE001 — app rejects lifespan
                started.set()

        import asyncio as _asyncio

        _asyncio.run_coroutine_threadsafe(lifespan(), self.loop)
        started.wait(timeout=30)
        self._state = state

    def _loop_main(self):
        import asyncio

        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def handle(self, request: dict) -> dict:
        import asyncio

        body = request.get("body", b"")
        incoming = [{"type": "http.request", "body": body,
                     "more_body": False}]
        out = {"status": 500, "headers": [], "body": b""}

        async def receive():
            if incoming:
                return incoming.pop(0)
            return {"type": "http.disconnect"}

        async def send(msg):
            if msg["type"] == "http.response.start":
                out["status"] = int(msg["status"])
                out["headers"] = [
                    (bytes(k).decode("latin1"), bytes(v).decode("latin1"))
                    for k, v in msg.get("headers", [])]
            elif msg["type"] == "http.response.body":
                out["body"] = out["body"] + bytes(msg.get("body", b""))

        scope = {
            "type": "http", "asgi": {"version": "3.0"},
            "http_version": "1.1",
            "method": request.get("method", "GET"),
            "path": request.get("path", "/"),
            "raw_path": request.get("path", "/").encode(),
            "query_string": request.get("query_string", b""),
            "headers": [(k.lower().encode("latin1"), v.encode("latin1"))
                        for k, v in request.get("headers", [])],
            "client": None, "server": None, "scheme": "http",
            "state": dict(self._state),
        }
        fut = asyncio.run_coroutine_threadsafe(
            self.app(scope, receive, send), self.loop)
        fut.result(timeout=30)
        return out


# --------------------------------------------------- decorator local state
# Per-process registry for decorator state that must not travel with the
# pickled wrapper (locks, queues, caches). Keyed by a uuid token baked into
# the wrapper closure; each process (driver, replica worker) materializes
# its own instance on first call.
_decorator_states: Dict[str, Any] = {}
_decorator_states_lock = threading.Lock()


def _decorator_state(token: str, factory):
    with _decorator_states_lock:
        st = _decorator_states.get(token)
        if st is None:
            st = _decorator_states[token] = factory()
        return st


# ----------------------------------------------------------------- batching
def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Dynamic request batching (reference role: serve/batching.py).

    Decorate a method taking a LIST of inputs and returning a LIST of
    outputs; concurrent callers are coalesced up to max_batch_size or until
    the wait timeout — the mechanism that keeps TPU serving on large
    batches. Thread-safe (replica actors may run with max_concurrency>1).
    """

    def wrap(fn):
        # Decorator state (lock + queue) is created lazily PER PROCESS via
        # a token-keyed registry: the wrapper must survive cloudpickle into
        # a replica's worker process, and a captured _thread.lock cannot.
        import uuid as _uuid

        token = _uuid.uuid4().hex

        def _state():
            return _decorator_state(
                token, lambda: {"lock": threading.Lock(), "pending": []})

        def flush(batch_items):
            args = [it[0] for it in batch_items]
            try:
                results = fn(batch_items[0][3], args) if batch_items[0][3] \
                    is not None else fn(args)
                if len(results) != len(args):
                    raise ValueError(
                        f"batched fn returned {len(results)} results for "
                        f"{len(args)} inputs")
                for it, res in zip(batch_items, results):
                    it[2]["value"] = res
                    it[1].set()
            except BaseException as exc:  # noqa: BLE001
                for it in batch_items:
                    it[2]["error"] = exc
                    it[1].set()

        @functools.wraps(fn)
        def wrapper(*call_args):
            # Support bound methods: (self, item) or plain (item,).
            if len(call_args) == 2:
                self_obj, arg = call_args
            else:
                self_obj, arg = None, call_args[0]
            event = threading.Event()
            slot: Dict[str, Any] = {}
            st = _state()
            lock, pending = st["lock"], st["pending"]
            with lock:
                pending.append((arg, event, slot, self_obj))
                is_leader = len(pending) == 1
            if is_leader:
                deadline = time.monotonic() + batch_wait_timeout_s
                while time.monotonic() < deadline:
                    with lock:
                        if len(pending) >= max_batch_size:
                            break
                    time.sleep(batch_wait_timeout_s / 10)
                # Drain everything queued (in max_batch_size chunks) before
                # abdicating: callers that joined after this leader's first
                # batch filled would otherwise wait with no one flushing.
                while True:
                    with lock:
                        batch_items = pending[:max_batch_size]
                        del pending[:len(batch_items)]
                    if not batch_items:
                        break
                    flush(batch_items)
            event.wait(timeout=30)
            if "error" in slot:
                raise slot["error"]
            return slot["value"]

        wrapper.__wrapped__ = fn
        return wrapper

    return wrap(_fn) if _fn is not None else wrap


# -------------------------------------------------------------- multiplexing
def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    """Per-replica LRU model cache (reference role: serve/multiplex.py).

    Decorate an async or sync model-loader method keyed by model_id; the
    wrapper evicts least-recently-used models beyond the cap.
    """

    def wrap(fn):
        import uuid as _uuid

        token = _uuid.uuid4().hex

        @functools.wraps(fn)
        def wrapper(self_or_id, model_id=None):
            st = _decorator_state(
                token,
                lambda: {"lock": threading.Lock(), "cache": OrderedDict()})
            lock, cache = st["lock"], st["cache"]
            if model_id is None:
                self_obj, mid = None, self_or_id
            else:
                self_obj, mid = self_or_id, model_id
            with lock:
                if mid in cache:
                    cache.move_to_end(mid)
                    return cache[mid]
            model = fn(mid) if self_obj is None else fn(self_obj, mid)
            if asyncio.iscoroutine(model):
                model = asyncio.get_event_loop().run_until_complete(model)
            with lock:
                cache[mid] = model
                cache.move_to_end(mid)
                while len(cache) > max_num_models_per_replica:
                    cache.popitem(last=False)
            return model

        wrapper.__wrapped__ = fn
        return wrapper

    return wrap(_fn) if _fn is not None else wrap
