"""DeploymentHandle / DeploymentResponse (reference role:
serve/handle.py — composable async handles whose responses chain)."""

from __future__ import annotations

import threading
from typing import Any, Optional

import ray_tpu


class DeploymentResponse:
    """Future for one routed request; passing it as an argument to another
    handle call chains without blocking (resolved at dispatch)."""

    def __init__(self, ref, replica_set, replica_key, replica=None):
        self._ref = ref
        self._rs = replica_set
        self._key = replica_key
        # Strong ref for the life of the in-flight key: the router keys
        # counts by id(replica), so the object must not be GC'd (and its id
        # recycled) while this response is pending.
        self._replica = replica
        self._released = False
        self._lock = threading.Lock()

    def result(self, timeout: Optional[float] = None) -> Any:
        try:
            return ray_tpu.get(self._ref, timeout=timeout)
        finally:
            self._release()

    def _release(self):
        with self._lock:
            if not self._released:
                self._released = True
                self._rs.release(self._key)
                self._replica = None

    def _to_object_ref(self):
        return self._ref

    def __del__(self):
        # Chained responses never see .result(); free the router slot so
        # queue-length telemetry (autoscaling) doesn't leak in-flight
        # counts forever.
        try:
            self._release()
        except Exception:  # noqa: BLE001 — interpreter-teardown safety
            pass


class DeploymentResponseGenerator:
    """Streaming response: iterate items as the replica's generator yields
    them (reference: handle.options(stream=True) generator semantics).
    Backed by the streaming task plane — the replica's ``handle_stream_gen``
    runs with ``num_returns="streaming"`` and each yield commits an item
    ref the ``ObjectRefGenerator`` hands out incrementally, so ``next()``
    unblocks on the replica's NEXT yield (no KV polling, and the producer
    honors the ``RAY_TPU_GENERATOR_BACKPRESSURE_ITEMS`` budget against
    this consumer). ``close()`` — or dropping the generator — cancels the
    in-flight replica generator between yields."""

    def __init__(self, ref_gen, replica_set, replica_key, replica=None):
        self._gen = ref_gen  # ObjectRefGenerator
        self._rs = replica_set
        self._key = replica_key
        self._replica = replica  # strong ref; see DeploymentResponse
        self._released = False
        self._lock = threading.Lock()

    def __iter__(self):
        return self

    def __next__(self):
        try:
            ref = next(self._gen)
        except BaseException:  # noqa: BLE001 — incl. StopIteration
            self._release()
            raise
        try:
            return ray_tpu.get(ref)
        except BaseException:  # noqa: BLE001 — lost item / typed error
            # A failed item materialization ends the stream for this
            # consumer: cancel the replica's generator (it must stop
            # doing unaccounted work / holding engine KV blocks) and
            # release the router slot so autoscaling stops counting it.
            try:
                self.close()
            except Exception:  # noqa: BLE001 — original error wins
                pass
            raise

    def close(self):
        """Stop consuming: cancels the replica's in-flight generator and
        releases committed-but-unconsumed items."""
        try:
            self._gen.close()
        finally:
            self._release()

    def _release(self):
        with self._lock:
            if not self._released:
                self._released = True
                self._rs.release(self._key)
                self._replica = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter-teardown safety
            pass


class _KVStreamFallbackGenerator:
    """THIN-CLIENT FALLBACK stream: items arrive through the driver KV
    under (stream_id, seq) keys, polled in order. Used only where the
    streaming actor plane is unavailable — a handle that crossed a
    process boundary (detached/pickled into a replica) or a replica
    hosted by a runtime without generator-method support. The primary
    path is ``DeploymentResponseGenerator`` over ``ObjectRefGenerator``;
    this poller trades latency (2 ms poll cadence, no backpressure) for
    working over nothing but the KV."""

    def __init__(self, ref, replica_set, replica_key, stream_id: str):
        self._inner = DeploymentResponse(ref, replica_set, replica_key)
        self._stream_id = stream_id
        self._seq = 0
        self._done = False

    def __iter__(self):
        return self

    def close(self):
        """Stop consuming: best-effort cancel of the producing replica
        task, release the router's in-flight slot NOW — an abandoned
        fallback stream must stop counting as an ongoing request (the
        autoscaler reads those counts) — and clean the stream's KV keys.
        Sweep protocol: if the producer already committed ``|end`` it has
        exited, so this side sweeps everything; otherwise a ``|cancel``
        marker is written and the still-running producer sweeps its own
        writes (covering items committed after this sweep)."""
        if self._done:
            return
        self._done = True
        try:
            ray_tpu.cancel(self._inner._to_object_ref())
        except Exception:  # noqa: BLE001 — cancel is advisory here
            pass
        try:
            from ray_tpu._private.worker import global_worker

            w = global_worker()
            base = f"serve|stream|{self._stream_id}"

            def sweep_items(seq):
                while w.kv_del(f"{base}|{seq}".encode()):
                    seq += 1
                return seq

            seq = sweep_items(self._seq)
            w.kv_del(f"{base}|err".encode())
            if w.kv_del(f"{base}|end".encode()):
                # Producer exited: re-sweep items it committed between
                # our first pass and |end landing (TOCTOU window).
                sweep_items(seq)
                w.kv_del(f"{base}|err".encode())
            else:
                # Producer still running: hand it the sweep baton — and
                # re-check |end, which closes the handshake against a
                # producer that committed |end before seeing the marker
                # (it re-checks |cancel after |end; we re-check |end
                # after |cancel, so one side always observes the other).
                w.kv_put(f"{base}|cancel".encode(), b"1")
                if w.kv_del(f"{base}|end".encode()):
                    sweep_items(seq)
                    w.kv_del(f"{base}|err".encode())
                    w.kv_del(f"{base}|cancel".encode())
        except Exception:  # noqa: BLE001 — KV cleanup is best-effort
            pass
        self._inner._release()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter-teardown safety
            pass

    def __next__(self):
        import pickle
        import time

        from ray_tpu._private.worker import global_worker

        if self._done:
            raise StopIteration
        w = global_worker()
        base = f"serve|stream|{self._stream_id}"
        deadline = time.monotonic() + 60.0
        oref = None
        try:
            oref = self._inner._to_object_ref()
        except Exception:  # noqa: BLE001 — no ref (test stub): poll only
            pass
        while True:
            raw = w.kv_get(f"{base}|{self._seq}".encode())
            if raw is not None:
                w.kv_del(f"{base}|{self._seq}".encode())
                self._seq += 1
                return pickle.loads(raw)
            err = w.kv_get(f"{base}|err".encode())
            if err is not None:
                w.kv_del(f"{base}|err".encode())
                self.close()  # sweep unconsumed items + markers
                raise pickle.loads(err)
            end = w.kv_get(f"{base}|end".encode())
            if end is not None and self._seq >= int(end):
                self.close()
                raise StopIteration
            if oref is not None:
                # Dead-producer fast path: a killed replica's in-flight
                # call materializes a typed error into the result ref
                # (ActorDiedError via the node-death watcher) — surface
                # it NOW so the client retries in seconds, instead of
                # burning the full stall bound per stream (a mid-kill
                # episode otherwise serializes every open stream behind
                # a 60 s poll timeout).
                call_err = w.store.peek_error(oref.object_id)
                if call_err is not None:
                    self.close()
                    raise call_err
            if time.monotonic() > deadline:
                self.close()
                raise TimeoutError("stream stalled for 60s")
            time.sleep(0.002)


class _DetachedRouter:
    """Controller stand-in for handles that crossed a process boundary
    (e.g. a handle passed into a replica's constructor): routes over a
    snapshot of the deployment's replica actor handles — which pickle —
    instead of the driver-local controller. Autoscaling changes after the
    snapshot are not observed (reference parity: handles cache their
    replica set and refresh from the controller; the refresh channel here
    is re-sending the handle). The deployment's admission config rides
    the snapshot too, enforced PER HANDLE-HOLDING PROCESS: in-flight
    counts aren't shared with the driver-side router (same caveat as the
    replica snapshot), so the bound is per caller, not global."""

    def __init__(self, replicas, admission=None):
        from ray_tpu.serve.router import ReplicaSet

        self._rs = ReplicaSet()
        self._rs.update(list(replicas))
        if admission:
            self._rs.configure_admission(admission.get("max_ongoing"),
                                         admission.get("fractions"))

    def _replica_set(self, name):
        return self._rs

    def _record_request(self, name):
        pass


def _rebuild_deployment_handle(name, method, stream, replicas,
                               priority=0, admission=None):
    handle = DeploymentHandle.__new__(DeploymentHandle)
    handle._name = name
    handle._controller = _DetachedRouter(replicas, admission=admission)
    handle._method = method
    handle._stream = stream
    handle._priority = priority
    return handle


def _extract_prefix_tokens(args, kwargs):
    """Token prompt of an LLM-shaped request, for prefix-aware routing:
    the first positional arg (or ``request=``) as either a token list or
    a dict carrying ``"prompt"``. Anything else returns None — non-LLM
    deployments route exactly as before."""
    req = args[0] if args else kwargs.get("request")
    if isinstance(req, dict):
        req = req.get("prompt")
    if (isinstance(req, (list, tuple)) and req
            and all(isinstance(t, int) for t in req)):
        return list(req)
    return None


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller,
                 method_name: str = "__call__", stream: bool = False,
                 priority: int = 0):
        self._name = deployment_name
        self._controller = controller
        self._method = method_name
        self._stream = stream
        self._priority = priority

    def __reduce__(self):
        rs = self._controller._replica_set(self._name)
        admission = {"max_ongoing": rs._max_ongoing,
                     "fractions": list(rs._class_fractions)}
        return (_rebuild_deployment_handle,
                (self._name, self._method, self._stream,
                 list(rs._replicas), self._priority, admission))

    def options(self, method_name: Optional[str] = None, *,
                stream: Optional[bool] = None,
                priority: Optional[int] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self._name, self._controller,
            method_name if method_name is not None else self._method,
            stream=self._stream if stream is None else stream,
            priority=self._priority if priority is None else int(priority))

    def remote(self, *args, **kwargs):
        from ray_tpu._private import tracing

        span = None
        if tracing._TRACER is not None:
            # Serve entry point: inherit the caller's ambient context
            # (or root a fresh trace) — the span covers routing, wake
            # and submission; replica/engine spans parent to it via the
            # actor payload and the request dict's _trace.
            span = tracing.begin("serve.request", deployment=self._name,
                                 method=self._method,
                                 priority=self._priority)
            # Only LLMServer deployments get the context injected into
            # their request dict (it lifts "_trace" into the engine
            # submit; the engine never sees the dict). Other
            # deployments' arguments are NEVER reshaped by tracing —
            # their spans come from the actor-call bridge.
            if self._targets_llm():
                if args and isinstance(args[0], dict) \
                        and "prompt" in args[0]:
                    args = ({**args[0],
                             "_trace": tracing.inject(span.ctx)},) \
                        + args[1:]
                elif isinstance(kwargs.get("request"), dict) \
                        and "prompt" in kwargs["request"]:
                    kwargs = dict(kwargs)
                    kwargs["request"] = {**kwargs["request"],
                                         "_trace":
                                         tracing.inject(span.ctx)}
        try:
            result = self._remote_inner(args, kwargs)
        except BaseException as exc:
            tracing.finish(span, status="error",
                           error=type(exc).__name__)
            raise
        tracing.finish(span)
        return result

    def _targets_llm(self) -> bool:
        """True when this deployment's underlying class consumes LLM
        request dicts (the ``_consumes_llm_requests`` marker, consulted
        through the controller). Detached (pickled) handles have no
        deployment registry — they skip injection; their trace still
        flows through the actor-op payload."""
        try:
            return self._controller.consumes_llm_requests(self._name)
        except Exception:  # noqa: BLE001 — detached router/thin client
            return False

    def _remote_inner(self, args, kwargs):
        rs = self._controller._replica_set(self._name)
        # Prefix-aware tier: when any replica has reported a prefix
        # digest (LLM deployments), score replicas by cached-prefix
        # overlap with the request's prompt — a hit routes the request
        # where its prefill is already cached.
        prefix_tokens = None
        if rs.has_prefix_digests():
            prefix_tokens = _extract_prefix_tokens(args, kwargs)
        # Priority admission: past the deployment's class threshold this
        # raises a typed RequestSheddedError before any replica is
        # touched — overload degrades by policy, not by timeout.
        try:
            key, replica = rs.choose(prefix_tokens=prefix_tokens,
                                     priority=self._priority)
        except RuntimeError:
            # Zero replicas: a scaled-to-zero deployment WAKES (the
            # request queues while the controller scales back up —
            # bounded) instead of failing; detached routers have no
            # controller and keep the raise. Bounded re-wake: the
            # woken replica can die between wake_and_wait returning
            # and the re-choose (a kill landing mid-wake) — retry the
            # wake instead of leaking the raw no-replica RuntimeError.
            wake = getattr(self._controller, "wake_and_wait", None)
            if wake is None:
                raise
            for attempt in range(3):
                wake(self._name)
                rs = self._controller._replica_set(self._name)
                try:
                    key, replica = rs.choose(
                        prefix_tokens=prefix_tokens,
                        priority=self._priority)
                    break
                except RuntimeError:
                    if attempt == 2:
                        raise
        # Chain: unwrap DeploymentResponses into ObjectRefs so downstream
        # deployments receive resolved values without blocking here.
        args = tuple(
            a._to_object_ref() if isinstance(a, DeploymentResponse) else a
            for a in args)
        kwargs = {
            k: (v._to_object_ref() if isinstance(v, DeploymentResponse)
                else v)
            for k, v in kwargs.items()
        }
        self._controller._record_request(self._name)
        if self._stream:
            try:
                # Primary: the streaming task plane — the replica's
                # generator yields straight into item refs this driver
                # consumes incrementally (with backpressure).
                ref_gen = replica.handle_stream_gen.options(
                    num_returns="streaming").remote(
                        self._method, args, kwargs)
                return DeploymentResponseGenerator(
                    ref_gen, rs, key, replica=replica)
            except (ValueError, AttributeError, TypeError):
                # Thin-client mode: the replica's runtime has no
                # streaming plane (cluster-placed / detached handle) —
                # fall back to (stream_id, seq) KV polling. TypeError is
                # the client-path signature: _ActorRuntime.submit hits
                # range("streaming") server-side.
                pass
            import uuid

            stream_id = uuid.uuid4().hex
            ref = replica.handle_stream.remote(
                self._method, args, kwargs, stream_id)
            return _KVStreamFallbackGenerator(ref, rs, key, stream_id)
        method = getattr(replica, "handle_request")
        ref = method.remote(self._method, args, kwargs)
        resp = DeploymentResponse(ref, rs, key, replica=replica)
        return resp

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return _MethodCaller(self, item)


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle.options(self._method).remote(*args, **kwargs)
