"""DeploymentHandle / DeploymentResponse (reference role:
serve/handle.py — composable async handles whose responses chain)."""

from __future__ import annotations

import threading
from typing import Any, Optional

import ray_tpu


class DeploymentResponse:
    """Future for one routed request; passing it as an argument to another
    handle call chains without blocking (resolved at dispatch)."""

    def __init__(self, ref, replica_set, replica_key, replica=None):
        self._ref = ref
        self._rs = replica_set
        self._key = replica_key
        # Strong ref for the life of the in-flight key: the router keys
        # counts by id(replica), so the object must not be GC'd (and its id
        # recycled) while this response is pending.
        self._replica = replica
        self._released = False
        self._lock = threading.Lock()

    def result(self, timeout: Optional[float] = None) -> Any:
        try:
            return ray_tpu.get(self._ref, timeout=timeout)
        finally:
            self._release()

    def _release(self):
        with self._lock:
            if not self._released:
                self._released = True
                self._rs.release(self._key)
                self._replica = None

    def _to_object_ref(self):
        return self._ref

    def __del__(self):
        # Chained responses never see .result(); free the router slot so
        # queue-length telemetry (autoscaling) doesn't leak in-flight
        # counts forever.
        try:
            self._release()
        except Exception:  # noqa: BLE001 — interpreter-teardown safety
            pass


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller,
                 method_name: str = "__call__"):
        self._name = deployment_name
        self._controller = controller
        self._method = method_name

    def options(self, method_name: str) -> "DeploymentHandle":
        return DeploymentHandle(self._name, self._controller, method_name)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        rs = self._controller._replica_set(self._name)
        key, replica = rs.choose()
        # Chain: unwrap DeploymentResponses into ObjectRefs so downstream
        # deployments receive resolved values without blocking here.
        args = tuple(
            a._to_object_ref() if isinstance(a, DeploymentResponse) else a
            for a in args)
        kwargs = {
            k: (v._to_object_ref() if isinstance(v, DeploymentResponse)
                else v)
            for k, v in kwargs.items()
        }
        method = getattr(replica, "handle_request")
        ref = method.remote(self._method, args, kwargs)
        resp = DeploymentResponse(ref, rs, key, replica=replica)
        self._controller._record_request(self._name)
        return resp

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return _MethodCaller(self, item)


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle.options(self._method).remote(*args, **kwargs)
