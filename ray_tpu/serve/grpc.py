"""gRPC ingress proxy (reference role: serve/_private/proxy.py gRPC
side — the reference runs a grpc.aio server whose generic handlers
route user-defined service methods to replicas [unverified]).

Generic-handler design, no protoc step: the proxy registers a
``grpc.GenericRpcHandler`` that accepts ANY unary-unary method of the
form ``/<package.Service>/<Method>``; the first metadata entry
``application`` (reference parity) or the service name's last path
segment selects the deployment, the gRPC method name selects the
replica method, and the request/response payloads are raw bytes the
user frames however they like (JSON by convention — the test uses it).
Routing rides the same pow-2 ReplicaSet as handle and HTTP calls.
"""

from __future__ import annotations

import json
import threading
from concurrent import futures
from typing import Optional

from ray_tpu.serve.controller import get_or_create_controller
from ray_tpu.serve.handle import DeploymentHandle


class _GenericHandler:
    """grpc.GenericRpcHandler: serves every method name dynamically."""

    def __init__(self):
        import grpc

        self._grpc = grpc

    def service(self, handler_call_details):
        grpc = self._grpc
        # /package.Service/Method -> (deployment?, method)
        _, _, rest = handler_call_details.method.partition("/")
        service, _, method = rest.partition("/")
        meta = dict(handler_call_details.invocation_metadata or ())
        deployment = meta.get("application") or service.split(".")[-1]

        def unary_unary(request: bytes, context):
            controller = get_or_create_controller()
            try:
                handle = DeploymentHandle(deployment, controller)
                payload = json.loads(request) if request else {}
                args = payload.get("args", [])
                kwargs = payload.get("kwargs", {})
                target = "__call__" if method in ("Call", "__call__") \
                    else method
                out = handle.options(target).remote(
                    *args, **kwargs).result(timeout=60)
                return json.dumps({"result": out}).encode()
            except KeyError:
                context.set_code(grpc.StatusCode.NOT_FOUND)
                context.set_details(
                    f"no deployment named {deployment!r}")
                return b""
            except Exception as exc:  # noqa: BLE001 — app error boundary
                context.set_code(grpc.StatusCode.INTERNAL)
                context.set_details(f"{type(exc).__name__}: {exc}")
                return b""

        return grpc.unary_unary_rpc_method_handler(
            unary_unary,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b)


class GRPCProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 9000):
        import grpc

        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="serve-grpc"))
        self._server.add_generic_rpc_handlers((_GenericHandler(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()

    def shutdown(self):
        self._server.stop(grace=0.5)


_proxy: Optional[GRPCProxy] = None
_lock = threading.Lock()


def start_grpc_proxy(host: str = "127.0.0.1",
                     port: int = 9000) -> GRPCProxy:
    global _proxy
    with _lock:
        if _proxy is None:
            _proxy = GRPCProxy(host, port)
        return _proxy


def stop_grpc_proxy():
    global _proxy
    with _lock:
        if _proxy is not None:
            _proxy.shutdown()
            _proxy = None
