"""ServeController: deployment reconciliation + autoscaling (reference
role: serve/_private/controller.py + deployment_state.py +
autoscaling_policy.py).

Target state (deployments + replica counts) vs actual state (live replica
actors) reconciled by a background loop; autoscaling adjusts target counts
from ongoing-request telemetry within [min_replicas, max_replicas].
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu._private.log import get_logger
from ray_tpu.serve.router import ReplicaSet

log = get_logger(__name__)


@dataclass
class AutoscalingConfig:
    """``min_replicas=0`` enables SCALE-TO-ZERO: past the downscale
    delay with no ongoing requests the deployment drops its last
    replica; the next request WAKES it (queues while the controller
    scales back up, bounded by ``RAY_TPU_SERVE_WAKE_TIMEOUT_S``)
    instead of shedding."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    # Scaling signal. The default scales on the router's per-replica
    # in-flight counts; any other name is polled FROM the replicas
    # (``autoscale_metric(name)``, off the request path on the
    # telemetry thread) and averaged — disaggregated pools scale each
    # on their own saturation signal ("queue_depth" for a prefill
    # pool's parked prompts, "kv_blocks_in_use" for a decode pool's
    # resident sequences) instead of one conflated stream count.
    metric: str = "ongoing_requests"
    # Per-replica target for a custom metric (None: falls back to
    # target_ongoing_requests, which only makes sense for metrics in
    # comparable units).
    target_value: Optional[float] = None


# Scale/wake event history is BOUNDED (observability, not a ledger): a
# long-lived deployment flapping for days must not grow memory or make
# every status() copy thousands of dicts.
_SCALE_EVENTS_MAX = 256


def _record_scale_event(events: List[dict], event: dict) -> None:
    events.append(event)
    if len(events) > _SCALE_EVENTS_MAX:
        del events[:len(events) - _SCALE_EVENTS_MAX]


@dataclass
class DeploymentInfo:
    name: str
    cls: type
    init_args: tuple
    init_kwargs: dict
    num_replicas: int
    autoscaling: Optional[AutoscalingConfig]
    max_ongoing_requests: Optional[int] = None
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    replicas: List[Any] = field(default_factory=list)
    replica_set: ReplicaSet = field(default_factory=ReplicaSet)
    status: str = "UPDATING"
    request_count: int = 0
    last_scale_change: float = 0.0
    last_prefix_poll: float = 0.0
    # Elasticity observability: every target change (autoscale up/down,
    # wake) as {"t_decision", "from", "to", "reason"} on the shared
    # monotonic clock — the serve half of the cold-start SLO pairing.
    scale_events: List[dict] = field(default_factory=list)
    wake_events: int = 0
    last_wake_latency_s: float = 0.0
    # Custom autoscaling metric samples, id(replica) -> last value
    # (polled on the telemetry thread; pruned with the replica list).
    metric_values: Dict[int, float] = field(default_factory=dict)
    last_metric_poll: float = 0.0


class ServeController:
    """In-process controller singleton (the reference runs this as a
    detached actor; here the runtime is process-local, so it is a
    supervisor object with a reconciler thread)."""

    def __init__(self):
        self._deployments: Dict[str, DeploymentInfo] = {}
        self._lock = threading.RLock()
        # Reconcile passes are MUTUALLY EXCLUSIVE: deploy(), the
        # background loop, lazy routing and the wake path all call
        # _reconcile_once — two concurrent passes would each observe
        # live < target and start duplicate replicas, orphaning the
        # loser's actor on its node (leaked load the autoscaler can
        # never drain). Separate from _lock: replica construction is
        # slow (engine init) and must not block status()/routing.
        self._reconcile_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._reconcile_loop, daemon=True,
            name="serve-controller")
        self._thread.start()
        # Prefix-digest telemetry on its OWN thread: a slow or dying
        # replica blocking a 2s poll must never delay autoscaling or
        # dead-replica replacement in the reconcile loop.
        self._prefix_thread = threading.Thread(
            target=self._prefix_poll_loop, daemon=True,
            name="serve-prefix-poll")
        self._prefix_thread.start()
        # Flight-recorder section: deployment/replica state in every
        # debug bundle (a stalled drain or wedged scale-up is read
        # straight out of the incident archive).
        from ray_tpu._private import flight as _flight

        if _flight.active():
            _flight.add_section("serve", self.status)

    # -------------------------------------------------------------- deploy
    def deploy(self, name: str, cls: type, init_args, init_kwargs,
               num_replicas: int,
               autoscaling: Optional[AutoscalingConfig],
               max_ongoing_requests: Optional[int] = None,
               ray_actor_options: Optional[Dict[str, Any]] = None
               ) -> None:
        with self._lock:
            old = self._deployments.get(name)
            info = DeploymentInfo(
                name=name, cls=cls, init_args=init_args,
                init_kwargs=init_kwargs, num_replicas=num_replicas,
                autoscaling=autoscaling,
                max_ongoing_requests=max_ongoing_requests,
                ray_actor_options=dict(ray_actor_options or {}))
            if old is not None:
                info.replicas = old.replicas
                info.replica_set = old.replica_set
            info.replica_set.configure_admission(max_ongoing_requests)
            self._deployments[name] = info
        from ray_tpu.exceptions import PlacementInfeasibleError

        try:
            self._reconcile_once()
        except PlacementInfeasibleError as exc:
            # Infeasible TODAY is a capacity condition, not a bug:
            # the ask parked as an unmet shape (autoscaler signal) and
            # the reconcile loop retries. Anything else (a broken
            # replica constructor) propagates to the deploy caller —
            # it would otherwise crash-loop silently forever.
            log.warning("initial reconcile for %r deferred (%r); the "
                        "reconcile loop retries as capacity appears",
                        name, exc)

    def delete(self, name: str) -> None:
        with self._lock:
            info = self._deployments.pop(name, None)
        if info:
            for r in info.replicas:
                ray_tpu.kill(r)

    def shutdown(self) -> None:
        self._stop.set()
        with self._lock:
            names = list(self._deployments)
        for n in names:
            self.delete(n)

    # ----------------------------------------------------------- reconcile
    def _reconcile_loop(self):
        while not self._stop.wait(0.25):
            try:
                self._autoscale()
                self._reconcile_once()
            except Exception as exc:  # keep the controller alive
                log.warning("serve reconcile pass failed; controller "
                            "continues: %r", exc)

    # ---------------------------------------------------- prefix telemetry
    _PREFIX_POLL_INTERVAL_S = 1.0

    def _prefix_poll_loop(self):
        while not self._stop.wait(0.5):
            try:
                self._poll_prefix_digests()
            except Exception as exc:  # telemetry best-effort
                log.debug("prefix-digest poll failed; routing uses "
                          "stale overlap scores: %r", exc)
            try:
                self._poll_autoscale_metrics()
            except Exception as exc:  # telemetry best-effort
                log.debug("autoscale-metric poll failed; scaling uses "
                          "stale samples: %r", exc)

    def _poll_prefix_digests(self):
        """Refresh each prefix-capable deployment's replica digest
        reports (LLM replicas expose ``prefix_digest()`` — the cached
        block-chain hashes). The router scores replicas by cached-prefix
        overlap from these reports, entirely off the request path;
        stale reports only cost a routing hit, never correctness."""
        now = time.monotonic()
        with self._lock:
            infos = [i for i in self._deployments.values()
                     if hasattr(i.cls, "prefix_digest")
                     and now - i.last_prefix_poll
                     > self._PREFIX_POLL_INTERVAL_S]
        for info in infos:
            info.last_prefix_poll = now
            for r in list(info.replicas):
                try:
                    ref = r.handle_request.remote("prefix_digest", (), {})
                    report = ray_tpu.get(ref, timeout=2.0)
                    info.replica_set.update_prefix_digest(
                        id(r), report["block_size"], report["digests"])
                except Exception as exc:  # telemetry best-effort
                    log.debug("replica prefix_digest probe failed: %r",
                              exc)

    def _poll_autoscale_metrics(self):
        """Refresh custom autoscaling metric samples: deployments whose
        ``AutoscalingConfig.metric`` is not the router-side default ask
        each replica for ``autoscale_metric(name)`` — off the request
        path, on the same cadence and thread as the prefix polls. A
        replica that fails the probe keeps its LAST sample until it is
        pruned with the replica list (stale beats absent for a scaling
        signal)."""
        now = time.monotonic()
        with self._lock:
            infos = [i for i in self._deployments.values()
                     if i.autoscaling is not None
                     and i.autoscaling.metric != "ongoing_requests"
                     and now - i.last_metric_poll
                     > self._PREFIX_POLL_INTERVAL_S]
        for info in infos:
            info.last_metric_poll = now
            metric = info.autoscaling.metric
            replicas = list(info.replicas)
            for r in replicas:
                try:
                    ref = r.handle_request.remote(
                        "autoscale_metric", (metric,), {})
                    info.metric_values[id(r)] = float(
                        ray_tpu.get(ref, timeout=2.0))
                except Exception as exc:  # telemetry best-effort
                    log.debug("replica autoscale_metric probe failed: "
                              "%r", exc)
            live = {id(r) for r in replicas}
            for k in list(info.metric_values):
                if k not in live:
                    del info.metric_values[k]

    def _reconcile_once(self):
        with self._reconcile_lock:
            self._reconcile_once_locked()

    def _reconcile_once_locked(self):
        with self._lock:
            infos = list(self._deployments.values())
        first_exc = None
        for info in infos:
            try:
                self._reconcile_deployment(info)
            except Exception as exc:  # noqa: BLE001 — one deployment's
                # infeasible placement must not starve the others'
                # reconciles; re-raised (first) so deploy()/wake
                # callers still observe it.
                if first_exc is None:
                    first_exc = exc
        if first_exc is not None:
            raise first_exc

    def _reconcile_deployment(self, info: DeploymentInfo):
        target = info.num_replicas
        # Replace dead replicas first (failure recovery) — with a
        # defensive kill: a replica marked dead by the liveness
        # plane may actually be alive on its node (heartbeat
        # hiccup), and silently dropping the handle would orphan
        # the node-side actor (leaked load that pins the node
        # against the autoscaler's idle reaper forever).
        live = []
        for r in info.replicas:
            if r._runtime.dead:
                try:
                    ray_tpu.kill(r)
                except Exception:  # noqa: BLE001 — truly gone
                    pass
            else:
                live.append(r)
        try:
            while len(live) < target:
                live.append(self._start_replica(info))
            while len(live) > target:
                ray_tpu.kill(live.pop())
            info.status = "HEALTHY"
        finally:
            # Commit whatever exists even when a start raised mid-pass
            # (infeasible placement awaiting an autoscaled node): an
            # already-started replica must be TRACKED — dropping it
            # would orphan its actor as phantom node load.
            info.replicas = live
            info.replica_set.update(live)

    def _start_replica(self, info: DeploymentInfo):
        user_cls = info.cls
        init_args, init_kwargs = info.init_args, info.init_kwargs

        @ray_tpu.remote
        class Replica:
            def __init__(self):
                self._user = user_cls(*init_args, **init_kwargs)

            def handle_request(self, method, args, kwargs):
                # User args travel packed in a tuple, so chained
                # DeploymentResponse ObjectRefs are nested one level deep —
                # resolve them here (the composition contract).
                args = tuple(
                    ray_tpu.get(a) if isinstance(a, ray_tpu.ObjectRef)
                    else a for a in args)
                kwargs = {
                    k: (ray_tpu.get(v) if isinstance(v, ray_tpu.ObjectRef)
                        else v)
                    for k, v in kwargs.items()
                }
                fn = (self._user if method == "__call__"
                      else getattr(self._user, method))
                if not callable(fn):
                    raise TypeError(
                        f"deployment {user_cls.__name__}.{method} is not "
                        f"callable")
                return fn(*args, **kwargs)

            def handle_stream_gen(self, method, args, kwargs):
                """Generator method on the streaming task plane: invoked
                with ``num_returns="streaming"``, so every yield commits
                one item ref the caller's ObjectRefGenerator consumes
                incrementally — the handle's ``next()`` unblocks on THIS
                replica's next yield, and the yield loop pauses at the
                backpressure budget when the consumer lags."""
                args = tuple(
                    ray_tpu.get(a) if isinstance(a, ray_tpu.ObjectRef)
                    else a for a in args)
                kwargs = {
                    k: (ray_tpu.get(v) if isinstance(v, ray_tpu.ObjectRef)
                        else v)
                    for k, v in kwargs.items()
                }
                fn = (self._user if method == "__call__"
                      else getattr(self._user, method))
                yield from fn(*args, **kwargs)

            def handle_stream(self, method, args, kwargs, stream_id):
                """THIN-CLIENT FALLBACK: items stream through the driver
                KV under (stream_id, seq) keys — the response generator on
                the caller side polls them in order. Kept for handles that
                crossed a process boundary (detached) or replica runtimes
                without the streaming actor plane; the primary path is
                ``handle_stream_gen`` above.

                Cancellation protocol: the consumer's ``close()`` writes a
                ``|cancel`` marker; this loop checks it each yield, and a
                cancelled (or cancel-raced) producer sweeps every key it
                wrote instead of committing ``|end`` — abandoned fallback
                streams must not leak their buffered payloads in the KV."""
                import pickle as _pickle

                from ray_tpu._private.worker import auto_init

                w = auto_init()
                base = f"serve|stream|{stream_id}"
                cancel_key = f"{base}|cancel".encode()
                args = tuple(
                    ray_tpu.get(a) if isinstance(a, ray_tpu.ObjectRef)
                    else a for a in args)
                kwargs = {
                    k: (ray_tpu.get(v) if isinstance(v, ray_tpu.ObjectRef)
                        else v)
                    for k, v in kwargs.items()
                }
                fn = (self._user if method == "__call__"
                      else getattr(self._user, method))
                seq = 0
                cancelled = False
                try:
                    for item in fn(*args, **kwargs):
                        if w.kv_get(cancel_key) is not None:
                            cancelled = True
                            break
                        w.kv_put(f"{base}|{seq}".encode(),
                                 _pickle.dumps(item, protocol=5))
                        seq += 1
                except Exception as exc:  # noqa: BLE001 — stream error
                    w.kv_put(f"{base}|err".encode(), _pickle.dumps(exc))
                # Cancel handshake (with handle._KVStreamFallbackGenerator
                # .close): the cancelled side owns the sweep, the normal
                # side owns committing |end. The producer re-checks the
                # marker AFTER putting |end and the consumer re-checks
                # |end AFTER putting |cancel, so whichever write lands
                # last, one side is guaranteed to observe the other and
                # run the sweep — no interleaving leaks a key.
                def sweep():
                    for i in range(seq):
                        w.kv_del(f"{base}|{i}".encode())
                    w.kv_del(f"{base}|err".encode())
                    w.kv_del(f"{base}|end".encode())
                    w.kv_del(cancel_key)

                if cancelled or w.kv_get(cancel_key) is not None:
                    sweep()
                    return seq
                w.kv_put(f"{base}|end".encode(), str(seq).encode())
                if w.kv_get(cancel_key) is not None:
                    sweep()  # close() raced our final check: we own it
                return seq

            def health_check(self):
                return True

        # Replicas serve concurrently (reference default: 100 ongoing
        # requests per replica) — required for @serve.batch to coalesce.
        # SPREAD placement: with a cluster attached, replicas land across
        # the node daemons (and the driver), so a deployment scales past
        # one machine — a no-op standalone. ray_actor_options
        # (num_cpus/resources) make the replica a REAL resource demand:
        # with no feasible node the placement raises (parking an unmet
        # shape for the autoscaler) and the reconcile loop retries as
        # nodes launch.
        replica_opts = dict(max_concurrency=100,
                            scheduling_strategy="SPREAD")
        replica_opts.update(info.ray_actor_options)  # user keys win
        return Replica.options(**replica_opts).remote()

    # ---------------------------------------------------------- autoscale
    def _autoscale(self):
        now = time.monotonic()
        with self._lock:
            infos = list(self._deployments.values())
        for info in infos:
            cfg = info.autoscaling
            if cfg is None:
                continue
            qlens = info.replica_set.queue_lengths()
            if not qlens:
                continue
            if cfg.metric != "ongoing_requests":
                # Custom pool signal (polled from the replicas): the
                # per-replica average vs its own target. No samples yet
                # -> hold steady rather than scale on a guess.
                vals = list(info.metric_values.values())
                if not vals:
                    continue
                ongoing = sum(vals) / len(vals)
                target = (cfg.target_value
                          if cfg.target_value is not None
                          else cfg.target_ongoing_requests)
            else:
                ongoing = sum(qlens) / len(qlens)
                target = cfg.target_ongoing_requests
            if (ongoing > target
                    and info.num_replicas < cfg.max_replicas
                    and now - info.last_scale_change > cfg.upscale_delay_s):
                _record_scale_event(info.scale_events, {
                    "t_decision": now, "from": info.num_replicas,
                    "to": info.num_replicas + 1, "reason": "load"})
                info.num_replicas += 1
                info.last_scale_change = now
            elif (ongoing < target / 2
                  and info.num_replicas > cfg.min_replicas
                  and now - info.last_scale_change > cfg.downscale_delay_s):
                if info.num_replicas == 1 and sum(qlens) > 0:
                    continue  # scale-to-zero never kills live streams
                _record_scale_event(info.scale_events, {
                    "t_decision": now, "from": info.num_replicas,
                    "to": info.num_replicas - 1, "reason": "idle"})
                info.num_replicas -= 1
                info.last_scale_change = now

    # ----------------------------------------------------------------- wake
    def wake_and_wait(self, name: str) -> None:
        """Scale-to-zero wake: a request hit a deployment with zero
        replicas. Raise the target back to one (recorded as a wake
        scale event) and QUEUE the caller until a replica is live —
        bounded by ``RAY_TPU_SERVE_WAKE_TIMEOUT_S``, past which a typed
        ``GetTimeoutError`` surfaces instead of an unbounded hang.
        Concurrent callers share the same wake: only the first bumps
        the target, everyone waits on the replica set."""
        import time as _time

        from ray_tpu._private import tracing
        from ray_tpu._private.config import GlobalConfig
        from ray_tpu.exceptions import (
            GetTimeoutError,
            PlacementInfeasibleError,
        )

        t0 = time.monotonic()
        # Traced wake: the whole scale-from-zero wait is one span, and
        # the context parks in the cold-start stash so the autoscaler's
        # node launch (running on ITS thread) joins this trace.
        span = tracing.begin("serve.wake", deployment=name) \
            if tracing.active() else None
        tracing.stash_cold_start()
        try:
            with self._lock:
                info = self._deployments.get(name)
                if info is None:
                    raise KeyError(f"no deployment named {name!r}")
                if info.num_replicas == 0:
                    info.wake_events += 1
                    _record_scale_event(info.scale_events, {
                        "t_decision": t0, "from": 0, "to": 1,
                        "reason": "wake"})
                    info.num_replicas = 1
                    info.last_scale_change = t0
            deadline = t0 + float(GlobalConfig.serve_wake_timeout_s)
            while time.monotonic() < deadline:
                try:
                    self._reconcile_once()
                except PlacementInfeasibleError as exc:  # capacity pending
                    log.debug("wake reconcile retry pending capacity: %r",
                              exc)
                with self._lock:
                    info = self._deployments.get(name)
                    size = info.replica_set.size() if info else 0
                if info is None:
                    raise KeyError(f"no deployment named {name!r}")
                if size > 0:
                    with self._lock:
                        info.last_wake_latency_s = time.monotonic() - t0
                    tracing.finish(span)
                    # Wake satisfied without a node launch consuming the
                    # stash: drop it, or the next unrelated launch inside
                    # the cold-start window adopts this finished trace.
                    tracing.clear_cold_start(span.ctx if span else None)
                    return
                _time.sleep(0.25)
            raise GetTimeoutError(
                f"deployment {name!r} did not wake from zero replicas "
                f"within {GlobalConfig.serve_wake_timeout_s:.0f}s "
                f"(RAY_TPU_SERVE_WAKE_TIMEOUT_S)")
        except BaseException:
            # Any exit but success must close the span AND restore the
            # thread's ambient context — a dangling wake context would
            # silently adopt every later span on this reused thread.
            tracing.finish(span, status="error")
            tracing.clear_cold_start(span.ctx if span else None)
            raise

    # ------------------------------------------------------------- queries
    def consumes_llm_requests(self, name: str) -> bool:
        """Whether the deployment's served class opted into LLM
        request-dict reshaping (the ``_consumes_llm_requests`` marker)
        — handles consult this instead of reading the deployment
        registry directly."""
        with self._lock:
            info = self._deployments.get(name)
        return bool(getattr(getattr(info, "cls", None),
                            "_consumes_llm_requests", False))

    def _replica_set(self, name: str) -> ReplicaSet:
        with self._lock:
            info = self._deployments.get(name)
        if info is None:
            raise KeyError(f"no deployment named {name!r}")
        # Lazily ensure replicas exist before first routing. An
        # infeasible placement (replica demand awaiting an autoscaled
        # node) is NOT a routing error: choose() then raises the
        # no-replica signal and the handle's wake/wait path queues the
        # request until capacity appears.
        if info.replica_set.size() == 0:
            from ray_tpu.exceptions import PlacementInfeasibleError

            try:
                self._reconcile_once()
            except PlacementInfeasibleError as exc:  # capacity pending
                log.debug("lazy reconcile for %r deferred: %r",
                          name, exc)
        return info.replica_set

    def _record_request(self, name: str):
        with self._lock:
            info = self._deployments.get(name)
            if info:
                info.request_count += 1

    def is_ingress(self, name: str) -> bool:
        with self._lock:
            info = self._deployments.get(name)
        return bool(info is not None
                    and getattr(info.cls, "__serve_ingress__", None))

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                name: {
                    "status": info.status,
                    "replicas": len(info.replicas),
                    "target_replicas": info.num_replicas,
                    "requests": info.request_count,
                    "queue_lengths": info.replica_set.queue_lengths(),
                    "admission": info.replica_set.admission_stats(),
                    "scale_events": [dict(e)
                                     for e in info.scale_events],
                    "wake_events": info.wake_events,
                    "last_wake_latency_s": info.last_wake_latency_s,
                }
                for name, info in self._deployments.items()
            }


_controller: Optional[ServeController] = None
_controller_lock = threading.Lock()


def get_or_create_controller() -> ServeController:
    global _controller
    with _controller_lock:
        if _controller is None or _controller._stop.is_set():
            _controller = ServeController()
        return _controller


def shutdown_controller():
    global _controller
    with _controller_lock:
        if _controller is not None:
            _controller.shutdown()
            _controller = None
