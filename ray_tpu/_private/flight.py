"""Black-box flight recorder: always-on process observability.

Reference role: the ``ray stack`` / py-spy stack sampling, ``ray
memory``, and dashboard state-dump tooling from PAPER.md's
observability chapter — the layer that makes a wedged *process* (not
just a request) explainable after the fact. Three pieces, one module:

- **Sampling wall profiler** — a daemon thread walks
  ``sys._current_frames()`` on a jittered interval and aggregates
  FOLDED stacks (``thread;mod:fn;mod:fn`` → count) into a bounded
  per-process table, exportable as collapsed format (flamegraph.pl /
  speedscope paste) or speedscope JSON. Armed by ``RAY_TPU_PROFILE``;
  pure-Python, no py-spy dependency, safe to leave running (the GIL
  serializes the sample against user code — cost is bounded by
  ``profile_hz`` × stack depth, gated ≥0.95 fan-out ratio by
  ``bench.py --suite flight_overhead``).
- **Structured event ring** — a bounded deque of ``(ts, kind, data)``
  tuples: state transitions, queue depths, lock-hold outliers (fed by
  ``util/sanitizer.py``'s tracked locks), GC pauses (a ``gc.callbacks``
  hook). Cheap enough to leave armed: recording is one tuple append
  under a leaf lock; off = one module-global ``is None`` branch (the
  ``chaos.py`` / ``tracing.py`` inertness idiom).
- **Watchdog escalation** — heartbeat-gap (``beat()`` feeds it),
  event-loop-lag (the watchdog loop times its own wake overshoot: a
  whole-process stall — GIL hog, swap storm, SIGSTOP — shows up as
  lag), and lock-hold-time (a tracked lock held past the threshold is
  the observable shape of a deadlock) watchdogs that, on firing, write
  an automatic LOCAL dump (all-thread stacks via faulthandler + a
  structured frame walk, the event ring, a metrics snapshot, chaos
  counters, registered subsystem sections) instead of printing and
  hoping. Rate-limited; fires are counted as a framework metrics gauge.

Collection is pull-based like the tracing plane: node daemons and the
head answer ``debug_dump`` on their existing servers, worker processes
(nothing can dial them) SPILL periodic bundle snapshots to
``RAY_TPU_FLIGHT_DIR`` where the hosting daemon merges them (newest
snapshot per file, stale bundles from reused pooled workers expired),
and ``ray_tpu.debug_dump()`` / ``util.state.cluster_dump()`` /
``ray-tpu debug`` assemble one directory-per-incident archive. Zero
new steady-state head RPCs: nothing moves until someone asks.

``RAY_TPU_FLIGHT`` arms the recorder (event ring + watchdogs + dump
plane); ``RAY_TPU_PROFILE`` additionally arms the sampler (and implies
the recorder). Both inherit to spawned daemons/workers, so one setting
arms the whole tree.
"""

from __future__ import annotations

import faulthandler
import gc
import json
import os
import random
import sys
import threading
import time
import traceback
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "FlightRecorder", "install", "install_from_env", "uninstall",
    "recorder", "active", "record_event", "beat", "note_lock_acquired",
    "note_lock_released", "note_watchdog_fire", "add_section",
    "remove_section", "note_artifact", "local_bundle", "auto_dump",
    "set_profiling", "read_spilled_bundles", "collapsed_stacks",
]

ENV_VAR = "RAY_TPU_FLIGHT"
ENV_PROFILE = "RAY_TPU_PROFILE"
ENV_DIR = "RAY_TPU_FLIGHT_DIR"
# Sentinel marking ENV_DIR as runtime-auto-pointed (a session dir)
# rather than operator-set: runtimes re-point only auto dirs, so an
# operator's explicit dump directory survives across the process tree.
ENV_DIR_AUTO = "RAY_TPU_FLIGHT_DIR_AUTO"
ENV_NODE = "RAY_TPU_FLIGHT_NODE"

# Recorder slot (chaos/tracing idiom): None = off, every hot-path site
# guards with one global load + `is None` branch. Provably inert when
# off (tests/test_flight.py pins zero threads, zero counters).
_FLIGHT: Optional["FlightRecorder"] = None

_install_lock = threading.Lock()


def _cfg(name: str, default):
    """Config flag with a bootstrap-safe fallback (flight arms in
    spawned processes before config is necessarily importable)."""
    try:
        from ray_tpu._private.config import GlobalConfig

        return type(default)(GlobalConfig.get(name))
    except Exception:  # noqa: BLE001 — config unavailable at bootstrap
        return default


def _truthy(raw: Optional[str]) -> bool:
    raw = (raw or "").strip().lower()
    return bool(raw) and raw not in ("0", "false", "off")


# ------------------------------------------------------------------ sampler
def _fold_frame(frame) -> List[str]:
    """Root→leaf folded frames for one thread: ``file.py:fn`` parts,
    depth-bounded (a pathological recursion must not balloon keys)."""
    parts: List[str] = []
    depth = 0
    while frame is not None and depth < 64:
        code = frame.f_code
        parts.append(
            f"{os.path.basename(code.co_filename)}:{code.co_name}")
        frame = frame.f_back
        depth += 1
    parts.reverse()
    return parts


class _StackSampler:
    """Jittered-interval wall sampler over ``sys._current_frames()``.

    Aggregates into ``{folded_stack: count}`` bounded at
    ``profile_max_stacks`` distinct stacks (overflow counts into
    ``stacks_dropped`` — the aggregate stays honest about truncation).
    The jitter (±50% of the period) keeps the sampler from phase-
    locking onto periodic work and systematically missing it."""

    def __init__(self, hz: float, max_stacks: int):
        self.period = 1.0 / max(float(hz), 0.1)
        self.max_stacks = max(int(max_stacks), 16)
        self._agg: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.samples_taken = 0
        self.stacks_dropped = 0
        self._running = threading.Event()
        self._running.set()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ray_tpu_flight_sampler")
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(
                self.period * random.uniform(0.5, 1.5)):
            if self._running.is_set():
                try:
                    self.sample_once()
                except Exception as exc:  # sampler must not die
                    from ray_tpu._private.log import get_logger

                    get_logger("flight").debug(
                        "stack sample failed: %r", exc)

    def sample_once(self) -> int:
        """One sweep over every live thread's current frame (the
        sampler's own thread excluded — it would otherwise be the
        hottest stack in every profile). Returns threads sampled."""
        skip = {threading.get_ident(), self._thread.ident}
        names = {t.ident: t.name for t in threading.enumerate()}
        n = 0
        for tid, frame in sys._current_frames().items():
            if tid in skip:
                continue
            folded = ";".join(
                [names.get(tid, f"tid-{tid}")] + _fold_frame(frame))
            with self._lock:
                if folded in self._agg:
                    self._agg[folded] += 1
                elif len(self._agg) < self.max_stacks:
                    self._agg[folded] = 1
                else:
                    self.stacks_dropped += 1
            n += 1
        self.samples_taken += 1
        return n

    def set_running(self, on: bool):
        (self._running.set if on else self._running.clear)()

    @property
    def running(self) -> bool:
        return self._running.is_set()

    def collapsed(self) -> List[str]:
        """Brendan-Gregg collapsed format: ``stack count`` lines,
        hottest first (flamegraph.pl / speedscope paste-ready)."""
        with self._lock:
            items = sorted(self._agg.items(), key=lambda kv: -kv[1])
        return [f"{stack} {count}" for stack, count in items]

    def speedscope(self, name: str = "ray_tpu") -> dict:
        """Minimal speedscope 'sampled' profile document."""
        with self._lock:
            items = list(self._agg.items())
        frames: List[dict] = []
        index: Dict[str, int] = {}
        samples, weights = [], []
        for stack, count in items:
            idxs = []
            for part in stack.split(";"):
                if part not in index:
                    index[part] = len(frames)
                    frames.append({"name": part})
                idxs.append(index[part])
            samples.append(idxs)
            weights.append(count)
        return {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "shared": {"frames": frames},
            "profiles": [{
                "type": "sampled", "name": name, "unit": "none",
                "startValue": 0, "endValue": max(sum(weights), 1),
                "samples": samples, "weights": weights,
            }],
        }

    def stop(self):
        self._stop.set()


# ----------------------------------------------------------------- recorder
class FlightRecorder:
    """Per-process flight-recorder state: event ring, optional sampler,
    watchdogs, section providers, and bundle assembly/spill."""

    def __init__(self, component: str = "driver", node: str = "",
                 profile: bool = False, spill: bool = False,
                 event_capacity: Optional[int] = None):
        self.component = component
        self.node = node
        self.pid = os.getpid()
        cap = event_capacity if event_capacity is not None \
            else _cfg("flight_event_capacity", 4096)
        self._events: "deque[tuple]" = deque(maxlen=max(int(cap), 16))
        self._ev_lock = threading.Lock()
        self.events_recorded = 0
        # Lock-hold plane (fed by sanitizer's TrackedLock): in-flight
        # holds for the deadlock scan, outlier thresholds for the ring.
        self._holds: Dict[tuple, tuple] = {}  # (tid, name) -> (t0, mono0)
        self._hold_lock = threading.Lock()
        self.lock_hold_outliers = 0
        # Heartbeat plane: name -> last-beat monotonic; _beat_fired
        # keeps one fire per gap episode (reset when beats resume).
        self._beats: Dict[str, float] = {}
        self._beat_fired: Dict[str, bool] = {}
        self._beat_lock = threading.Lock()
        # In-flight task plane (worker processes / executor threads
        # mark task start/finish): tid -> (name, mono0, fired) for the
        # task-stuck watchdog — a deliberately hung task auto-dumps
        # without operator action.
        self._tasks: Dict[int, list] = {}
        self._task_lock = threading.Lock()
        # Watchdog escalation state.
        self.watchdog_fires = 0
        self.watchdog_last: "deque[tuple]" = deque(maxlen=32)
        self._dump_lock = threading.Lock()
        self._last_dump_mono = 0.0
        # Registered subsystem sections (scheduler depths, LLM KV
        # occupancy, serve deployments, ...) rendered at dump time.
        self._sections: Dict[str, Callable[[], Any]] = {}
        self._sections_lock = threading.Lock()
        # Device-profiler artifacts produced this session (xplane /
        # TensorBoard dirs from util.profiling.profile_trace).
        self._artifacts: List[str] = []
        # Dump / spill directory (workers inherit it from the hosting
        # runtime's environment, daemons point it at their session dir).
        self.dump_dir = os.environ.get(ENV_DIR) or _cfg("flight_dir", "")
        self.sampler: Optional[_StackSampler] = None
        if profile:
            self.sampler = _StackSampler(
                _cfg("profile_hz", 19.0),
                _cfg("profile_max_stacks", 2048))
        # GC-pause hook: phase timing via gc.callbacks — a pause past
        # flight_gc_ms becomes an event (GC is a classic invisible
        # source of tail latency).
        self._gc_t0: Optional[float] = None
        self._gc_min_s = _cfg("flight_gc_ms", 20.0) / 1000.0
        gc.callbacks.append(self._on_gc)
        self._stop = threading.Event()
        # Watchdog loop: one thread checks every condition; its own
        # wake overshoot IS the event-loop-lag probe.
        self._wd_period = max(_cfg("flight_watchdog_period_s", 1.0), 0.05)
        self._wd_thread = threading.Thread(
            target=self._watchdog_loop, daemon=True,
            name="ray_tpu_flight_watchdog")
        self._wd_thread.start()
        # Worker-process spill: nothing can dial a worker, so a fresh
        # bundle snapshot lands in ENV_DIR every period (first one
        # immediately — a short-lived worker still leaves a trace).
        self._spill_path: Optional[str] = None
        self._spill_records = 0
        self._spill_cap = max(int(_cfg("flight_spill_max_records", 8)), 1)
        self._spill_thread = None
        if spill and self.dump_dir:
            try:
                os.makedirs(self.dump_dir, exist_ok=True)
                self._spill_path = os.path.join(
                    self.dump_dir,
                    f"bundle-{self.pid}-{uuid.uuid4().hex[:8]}.jsonl")
            except OSError:
                self._spill_path = None
            if self._spill_path:
                self._spill_thread = threading.Thread(
                    target=self._spill_loop, daemon=True,
                    name="ray_tpu_flight_spill")
                self._spill_thread.start()

    # ------------------------------------------------------------ identity
    def set_identity(self, component: Optional[str] = None,
                     node: Optional[str] = None):
        if component is not None:
            self.component = component
        if node is not None:
            self.node = node

    # -------------------------------------------------------------- events
    def record(self, kind: str, data: Optional[dict] = None) -> None:
        with self._ev_lock:
            self._events.append((time.time(), kind, data))
            self.events_recorded += 1

    def events(self) -> List[dict]:
        with self._ev_lock:
            recs = list(self._events)
        return [{"ts": float(ts), "kind": kind,
                 "data": {str(k): _jsonable(v)
                          for k, v in (data or {}).items()}}
                for ts, kind, data in recs]

    def _on_gc(self, phase: str, info: dict):
        if phase == "start":
            self._gc_t0 = time.monotonic()
        elif phase == "stop" and self._gc_t0 is not None:
            dur = time.monotonic() - self._gc_t0
            self._gc_t0 = None
            if dur >= self._gc_min_s:
                self.record("gc.pause", {
                    "ms": round(dur * 1000.0, 3),
                    "generation": info.get("generation"),
                    "collected": info.get("collected")})

    # ---------------------------------------------------------- lock plane
    def note_lock_acquired(self, name: str) -> None:
        with self._hold_lock:
            self._holds[(threading.get_ident(), name)] = (
                time.time(), time.monotonic())

    def note_lock_released(self, name: str) -> None:
        key = (threading.get_ident(), name)
        with self._hold_lock:
            entry = self._holds.pop(key, None)
        if entry is None:
            return
        held_s = time.monotonic() - entry[1]
        if held_s * 1000.0 >= _cfg("flight_lock_hold_ms", 50.0):
            self.lock_hold_outliers += 1
            self.record("lock.hold", {"lock": name,
                                      "ms": round(held_s * 1000.0, 3)})

    # ------------------------------------------------------ heartbeat plane
    def beat(self, name: str) -> None:
        with self._beat_lock:
            self._beats[name] = time.monotonic()
            self._beat_fired[name] = False

    def clear_beat(self, name: str) -> None:
        """Retire a heartbeat feed (its loop is shutting down cleanly):
        a retired name can never gap-fire — without this, a healthy
        process that STOPPED beating on purpose (ray_tpu.shutdown())
        would report a stall ~gap seconds later."""
        with self._beat_lock:
            self._beats.pop(name, None)
            self._beat_fired.pop(name, None)

    # ----------------------------------------------------------- task plane
    def note_task_started(self, name: str) -> None:
        with self._task_lock:
            self._tasks[threading.get_ident()] = [
                str(name), time.monotonic(), False]

    def note_task_finished(self) -> None:
        with self._task_lock:
            self._tasks.pop(threading.get_ident(), None)

    # ------------------------------------------------------- watchdog loop
    def _watchdog_loop(self):
        while True:
            # Bounds re-read each tick: tests (and live operators via
            # GlobalConfig.set) tune thresholds without a restart, and
            # a bootstrap-time config import failure doesn't freeze
            # fallback values in for the process's whole life.
            lag_bound = _cfg("flight_loop_lag_s", 2.0)
            gap_bound = _cfg("flight_heartbeat_gap_s", 30.0)
            hold_bound = _cfg("flight_lock_watchdog_s", 10.0)
            t0 = time.monotonic()
            if self._stop.wait(self._wd_period):
                return
            lag = time.monotonic() - t0 - self._wd_period
            try:
                # Event-loop lag: this thread asked to sleep period
                # seconds; waking `lag` late means NO thread was being
                # scheduled promptly — the whole-process stall shape.
                if lag > lag_bound:
                    self._fire("loop-lag",
                               f"watchdog wake {lag:.2f}s late "
                               f"(bound {lag_bound}s) — process-wide "
                               f"scheduling stall")
                now = time.monotonic()
                with self._beat_lock:
                    gaps = [(n, now - last)
                            for n, last in self._beats.items()
                            if now - last > gap_bound
                            and not self._beat_fired.get(n)]
                    for n, _ in gaps:
                        self._beat_fired[n] = True
                for n, gap in gaps:
                    self._fire("heartbeat-gap",
                               f"{n!r} last beat {gap:.1f}s ago "
                               f"(bound {gap_bound}s)")
                # Task-stuck: an executing task past the bound is the
                # hung-worker shape — one fire per task episode (the
                # entry's fired flag), diagnostics only, never a kill.
                stuck_bound = _cfg("flight_task_stuck_s", 300.0)
                with self._task_lock:
                    hung = []
                    for entry in self._tasks.values():
                        if (not entry[2]
                                and now - entry[1] > stuck_bound):
                            entry[2] = True
                            hung.append((entry[0], now - entry[1]))
                for tname, dur in hung:
                    self._fire("task-stuck",
                               f"task {tname!r} executing for "
                               f"{dur:.1f}s (bound {stuck_bound}s) — "
                               f"hung worker or runaway task")
                with self._hold_lock:
                    stuck = [(name, now - mono0)
                             for (_tid, name), (_t0, mono0)
                             in self._holds.items()
                             if now - mono0 > hold_bound]
                for name, held in stuck:
                    # One fire per episode: drop the entry so a truly
                    # deadlocked lock doesn't re-fire every tick (its
                    # release can never pop it).
                    with self._hold_lock:
                        for key in [k for k in self._holds
                                    if k[1] == name]:
                            self._holds.pop(key, None)
                    self._fire("lock-hold",
                               f"tracked lock {name!r} held "
                               f"{held:.1f}s (bound {hold_bound}s) — "
                               f"deadlock or lock-held-across-I/O")
            except Exception as exc:  # watchdog must not die
                from ray_tpu._private.log import get_logger

                get_logger("flight").warning(
                    "watchdog check failed: %r", exc)

    def _fire(self, kind: str, message: str):
        self.watchdog_fires += 1
        self.watchdog_last.append((time.time(), kind, message))
        self.record(f"watchdog.{kind}", {"message": message})
        from ray_tpu._private.log import get_logger

        get_logger("flight").error(
            "watchdog %s fired: %s — capturing local dump", kind, message)
        self.auto_dump(kind)

    # ------------------------------------------------------------- bundles
    def stacks(self) -> Dict[str, List[str]]:
        """Structured all-thread stacks RIGHT NOW (frame walk — the
        JSON-friendly twin of the faulthandler text dump)."""
        names = {t.ident: t.name for t in threading.enumerate()}
        out: Dict[str, List[str]] = {}
        for tid, frame in sys._current_frames().items():
            rendered = [
                f"{fs.filename}:{fs.lineno} {fs.name}"
                for fs in traceback.extract_stack(frame)]
            out[f"{names.get(tid, 'tid')}-{tid}"] = rendered
        return out

    def local_bundle(self, include_dir: bool = False) -> dict:
        """This process's flight bundle: identity, all-thread stacks,
        event ring, profile aggregate, metrics snapshot, chaos
        counters, watchdog state, registered subsystem sections, and
        (``include_dir``, daemons only) the newest spilled bundle per
        hosted worker process."""
        bundle: Dict[str, Any] = {
            "ts": time.time(),
            "pid": self.pid,
            "component": self.component,
            "node": self.node,
            "argv": list(sys.argv),
            "stacks": self.stacks(),
            "events": self.events(),
            "events_recorded": self.events_recorded,
            "watchdog_fires": self.watchdog_fires,
            "watchdog_last": [
                {"ts": ts, "kind": k, "message": m}
                for ts, k, m in list(self.watchdog_last)],
            "lock_hold_outliers": self.lock_hold_outliers,
            "artifacts": list(self._artifacts),
        }
        now = time.monotonic()
        with self._task_lock:
            bundle["tasks_in_flight"] = [
                {"name": name, "running_s": round(now - mono0, 3)}
                for name, mono0, _fired in self._tasks.values()]
        s = self.sampler
        bundle["profile"] = {
            "armed": s is not None,
            "running": bool(s and s.running),
            "samples_taken": s.samples_taken if s else 0,
            "stacks_dropped": s.stacks_dropped if s else 0,
            "collapsed": s.collapsed() if s else [],
        }
        try:
            from ray_tpu.util.metrics import export_prometheus

            bundle["metrics"] = export_prometheus()
        except Exception:  # noqa: BLE001 — metrics plane optional
            bundle["metrics"] = ""
        try:
            from ray_tpu._private.chaos import wire_counters

            bundle["chaos"] = wire_counters()
        except Exception:  # noqa: BLE001 — chaos plane optional
            bundle["chaos"] = {}
        try:
            # Span-ring tail (tracing armed): the last slice of what
            # this process was doing request-wise, bounded so a full
            # 64k ring cannot balloon the bundle.
            from ray_tpu._private import tracing

            t = tracing.tracer()
            if t is not None:
                spans = t.dump(include_dir=False)
                bundle["spans_recorded"] = t.spans_recorded
                bundle["span_tail"] = spans[-256:]
            else:
                bundle["spans_recorded"] = 0
                bundle["span_tail"] = []
        except Exception:  # noqa: BLE001 — tracing plane optional
            bundle["span_tail"] = []
        bundle["sections"] = self._render_sections()
        if self.dump_dir:
            try:
                bundle["incidents"] = sorted(
                    f for f in os.listdir(self.dump_dir)
                    if f.startswith("incident-"))
            except OSError:
                bundle["incidents"] = []
        if include_dir:
            bundle["workers"] = read_spilled_bundles(
                self.dump_dir, exclude_pid=self.pid)
        return bundle

    def _render_sections(self, timeout_s: float = 2.0) -> Dict[str, Any]:
        """Render each registered section in its OWN bounded daemon
        thread: providers take subsystem locks (the head's state lock,
        the scheduler lock, serve's controller lock) — and when a dump
        fires BECAUSE one of those locks is wedged, a synchronous call
        would hang the watchdog thread forever instead of dumping.
        A section that doesn't answer in time reports itself blocked
        (which is itself diagnostic data); its thread is daemon and
        dumps are rate-limited, so a stuck renderer leaks at most one
        parked thread per dump interval."""
        with self._sections_lock:
            providers = dict(self._sections)
        results: Dict[str, Any] = {}
        threads = []
        for name, fn in providers.items():
            def render(name=name, fn=fn):
                try:
                    results[name] = _jsonable(fn())
                except Exception as exc:  # noqa: BLE001 — one section
                    results[name] = {"error": repr(exc)}

            t = threading.Thread(
                target=render, daemon=True,
                name=f"ray_tpu_flight_section_{name}")
            t.start()
            threads.append((name, t))
        deadline = time.monotonic() + timeout_s
        for name, t in threads:
            t.join(max(deadline - time.monotonic(), 0.0))
            if t.is_alive() and name not in results:
                results[name] = {
                    "error": f"section {name!r} blocked for "
                             f">{timeout_s}s (lock wedged?)"}
        return results

    def add_section(self, name: str, fn: Callable[[], Any]) -> None:
        with self._sections_lock:
            self._sections[name] = fn

    def remove_section(self, name: str) -> None:
        with self._sections_lock:
            self._sections.pop(name, None)

    def note_artifact(self, path: str) -> None:
        if path and path not in self._artifacts:
            self._artifacts.append(path)

    # ---------------------------------------------------------- auto dump
    def auto_dump(self, reason: str) -> Optional[str]:
        """Write this process's bundle to the flight dir NOW (watchdog
        escalation path). Rate-limited: a flapping watchdog must not
        fill the disk. Returns the incident path (None when
        rate-limited or the dir is unwritable)."""
        with self._dump_lock:
            now = time.monotonic()
            if (self._last_dump_mono and now - self._last_dump_mono
                    < _cfg("flight_dump_min_interval_s", 5.0)):
                return None
            self._last_dump_mono = now
        dump_dir = self.dump_dir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "ray_tpu_flight")
        stamp = time.strftime("%Y%m%d-%H%M%S")
        base = os.path.join(
            dump_dir, f"incident-{stamp}-{reason}-{self.pid}")
        try:
            os.makedirs(dump_dir, exist_ok=True)
            # faulthandler first: it renders C-level thread state with
            # minimal machinery — if bundle assembly itself wedges or
            # raises, the raw stacks are already on disk.
            with open(base + ".stacks.txt", "w") as f:
                faulthandler.dump_traceback(file=f, all_threads=True)
            with open(base + ".json", "w") as f:
                json.dump(self.local_bundle(), f)
        except OSError:
            return None
        return base + ".json"

    # -------------------------------------------------------------- spill
    def _spill_loop(self):
        period = max(_cfg("flight_spill_period_s", 5.0), 0.05)
        self.spill_once()  # short-lived workers still leave one snapshot
        while not self._stop.wait(period * random.uniform(0.8, 1.2)):
            self.spill_once()

    def spill_once(self) -> None:
        """Append one bundle snapshot line to this worker's spill file,
        rotating at capacity (restart at the newest window — the same
        bound the tracing spill uses) so a long-lived pooled worker's
        file stays O(capacity), not O(run)."""
        if not self._spill_path:
            return
        try:
            line = json.dumps(self.local_bundle()) + "\n"
            mode = "a"
            if self._spill_records >= self._spill_cap:
                mode = "w"
                self._spill_records = 0
            with open(self._spill_path, mode) as f:
                f.write(line)
            self._spill_records += 1
        except (OSError, ValueError):
            self._spill_path = None  # disk gone: ring-only from here

    # --------------------------------------------------------------- stop
    def stop(self):
        self._stop.set()
        if self.sampler is not None:
            self.sampler.stop()
        try:
            gc.callbacks.remove(self._on_gc)
        except ValueError:
            pass


def _jsonable(v):
    """Best-effort JSON-serializable projection (sections return
    arbitrary subsystem dicts; a stray object must not kill a dump)."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_jsonable(x) for x in v]
    return repr(v)


def read_spilled_bundles(spill_dir: Optional[str],
                         exclude_pid: Optional[int] = None,
                         stale_s: Optional[float] = None) -> List[dict]:
    """Newest bundle snapshot per spill file under ``spill_dir``.

    Skips files this process wrote itself (its live state supersedes
    them) and snapshots older than ``stale_s`` (default
    ``flight_bundle_stale_s``): worker processes are POOLED — a file
    left by a worker that since exited or was re-leased to another
    runtime must not masquerade as a live process in an assembled
    incident."""
    if not spill_dir:
        return []
    if stale_s is None:
        stale_s = _cfg("flight_bundle_stale_s", 120.0)
    prefix_self = f"bundle-{exclude_pid}-" if exclude_pid else None
    try:
        names = sorted(os.listdir(spill_dir))
    except OSError:
        return []
    out: List[dict] = []
    now = time.time()
    for name in names:
        if not name.startswith("bundle-") or not name.endswith(".jsonl"):
            continue
        if prefix_self and name.startswith(prefix_self):
            continue
        last = None
        try:
            with open(os.path.join(spill_dir, name)) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        last = line
        except OSError:
            continue
        if not last:
            continue
        try:
            bundle = json.loads(last)
        except ValueError:
            continue  # racing writer mid-line / rotation
        if now - float(bundle.get("ts", 0.0)) > stale_s:
            continue
        out.append(bundle)
    return out


# ------------------------------------------------------------ installation
def install(component: str = "driver", node: str = "",
            profile: bool = False, spill: bool = False,
            event_capacity: Optional[int] = None) -> FlightRecorder:
    """Arm the flight recorder process-wide (idempotent per process: a
    second install re-labels the existing recorder — and upgrades it
    with a sampler if ``profile=True`` arrived late)."""
    global _FLIGHT
    with _install_lock:
        if _FLIGHT is not None:
            _FLIGHT.set_identity(component=component, node=node or None)
            if profile and _FLIGHT.sampler is None:
                _FLIGHT.sampler = _StackSampler(
                    _cfg("profile_hz", 19.0),
                    _cfg("profile_max_stacks", 2048))
            return _FLIGHT
        _FLIGHT = FlightRecorder(
            component=component, node=node, profile=profile,
            spill=spill, event_capacity=event_capacity)
        return _FLIGHT


def install_from_env(component: str = "driver",
                     spill: bool = False) -> Optional[FlightRecorder]:
    """Arm iff ``RAY_TPU_FLIGHT`` or ``RAY_TPU_PROFILE`` is truthy
    (profiling implies the recorder); inert None otherwise."""
    armed = _truthy(os.environ.get(ENV_VAR))
    profiled = _truthy(os.environ.get(ENV_PROFILE))
    if not (armed or profiled):
        return None
    return install(component=component,
                   node=os.environ.get(ENV_NODE, ""),
                   profile=profiled, spill=spill)


def uninstall() -> None:
    """Disarm and stop the recorder's threads (test boundaries)."""
    global _FLIGHT
    with _install_lock:
        rec, _FLIGHT = _FLIGHT, None
    if rec is not None:
        rec.stop()


def recorder() -> Optional[FlightRecorder]:
    return _FLIGHT


def active() -> bool:
    return _FLIGHT is not None


# ----------------------------------------------------- module-level facade
# Every site below is the one-global-load + `is None` inertness branch.
def record_event(kind: str, **data) -> None:
    r = _FLIGHT
    if r is None:
        return
    r.record(kind, data or None)


def beat(name: str) -> None:
    r = _FLIGHT
    if r is None:
        return
    r.beat(name)


def note_lock_acquired(name: str) -> None:
    r = _FLIGHT
    if r is None:
        return
    r.note_lock_acquired(name)


def note_lock_released(name: str) -> None:
    r = _FLIGHT
    if r is None:
        return
    r.note_lock_released(name)


def clear_beat(name: str) -> None:
    r = _FLIGHT
    if r is None:
        return
    r.clear_beat(name)


def note_task_started(name: str) -> None:
    r = _FLIGHT
    if r is None:
        return
    r.note_task_started(name)


def note_task_finished() -> None:
    r = _FLIGHT
    if r is None:
        return
    r.note_task_finished()


def note_watchdog_fire(kind: str, message: str) -> None:
    """External watchdogs (the sanitizer's StallWatchdog) escalate
    through here: counted, ringed, and auto-dumped like the built-ins."""
    r = _FLIGHT
    if r is None:
        return
    r._fire(kind, message)


def add_section(name: str, fn: Callable[[], Any]) -> None:
    r = _FLIGHT
    if r is None:
        return
    r.add_section(name, fn)


def remove_section(name: str) -> None:
    r = _FLIGHT
    if r is None:
        return
    r.remove_section(name)


def note_artifact(path: str) -> None:
    r = _FLIGHT
    if r is None:
        return
    r.note_artifact(path)


def local_bundle(include_dir: bool = False) -> Optional[dict]:
    r = _FLIGHT
    if r is None:
        return None
    return r.local_bundle(include_dir=include_dir)


def auto_dump(reason: str) -> Optional[str]:
    r = _FLIGHT
    if r is None:
        return None
    return r.auto_dump(reason)


def set_profiling(on: bool) -> bool:
    """Pause/resume the sampler (the ``flight_ctl`` wire verb — the
    bench A/B and live operators toggle cluster-wide sampling without
    restarting anything). Returns the new running state."""
    r = _FLIGHT
    if r is None or r.sampler is None:
        return False
    r.sampler.set_running(bool(on))
    return r.sampler.running


def collapsed_stacks() -> List[str]:
    r = _FLIGHT
    if r is None or r.sampler is None:
        return []
    return r.sampler.collapsed()
