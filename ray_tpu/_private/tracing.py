"""Cluster-wide distributed tracing plane.

Reference role: Ray's OpenTelemetry-style tracing hooks plus the task
event pipeline feeding ``ray timeline`` (PAPER.md §2.7) — here one
process-local span ring per process with trace CONTEXT propagated on
every wire hop, so one request assembles into one cross-process trace:

- A :class:`TraceContext` (trace_id, span_id) is minted at public entry
  points (``.remote()``, serve handles, the HTTP proxy, LLM ``submit``,
  workflow steps) and rides the wire: task payload dicts through the
  remote router's direct dispatch, ``object_meta`` frames on the peer
  pull plane, streaming ``item_done`` reports, serve/LLM request dicts,
  and ``RAY_TPU_TRACE_PARENT`` in the environment of autoscaler-launched
  node daemons (the cold-start chain: launch → join → replica init →
  first token).
- Each process records COMPLETED spans into a bounded deque
  (``RAY_TPU_TRACE_MAX_SPANS``), the same ring idiom as
  ``task_events.py``. Collection is pull-based: node daemons answer a
  ``trace_dump`` request on their direct server, the head answers a
  ``trace_dump`` RPC, and ``util.state.trace_summary()`` /
  ``ray_tpu.timeline(trace_id=...)`` assemble the cluster-wide view.
- Worker processes (no dialable server) SPILL finished spans to
  ``RAY_TPU_TRACE_DIR/spans-<pid>-*.jsonl``; the hosting daemon's
  ``trace_dump`` merges those files, so replica/worker spans surface
  through the daemon that owns them.

Off by default. With tracing off the module-global ``_TRACER`` slot is
``None`` and every instrumentation point pays ONE global load + ``is
None`` branch (the ``chaos.py`` inertness idiom): no span allocation,
no extra payload keys, no extra frame bytes. ``RAY_TPU_TRACE`` (any
truthy value — inherited by spawned daemons/workers, so one setting
traces the whole tree) or programmatic :func:`install` activates it.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "TraceContext", "Tracer", "install", "install_from_env", "uninstall",
    "tracer", "active", "new_trace", "begin", "finish", "start_span",
    "event", "inject", "extract", "current_context", "use_context",
    "register_task", "task_context", "on_task_event", "stash_cold_start",
    "take_cold_start", "take_cold_start_timed", "clear_cold_start",
    "cold_start_parent",
    "encode_cold_start_parent", "local_spans", "chrome_trace",
]

ENV_VAR = "RAY_TPU_TRACE"
ENV_DIR = "RAY_TPU_TRACE_DIR"
ENV_PARENT = "RAY_TPU_TRACE_PARENT"
ENV_NODE = "RAY_TPU_TRACE_NODE"

# Tracing slot (chaos idiom): None = off, every hot-path site guards
# with one global load + `is None` branch. Provably inert when off.
_TRACER: Optional["Tracer"] = None

# Terminal task states the task-event bridge closes exec spans on.
_TERMINAL = ("FINISHED", "FAILED")

_tls = threading.local()


class TraceContext:
    """One position in a trace: (trace_id, span_id). ``span_id`` is the
    span new children parent to. Wire form: a ``(trace_id, span_id)``
    tuple of hex strings (msgpack/pickle friendly, 0 parsing)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def encode(self) -> str:
        return f"{self.trace_id}:{self.span_id}"

    @classmethod
    def decode(cls, s: str) -> Optional["TraceContext"]:
        try:
            trace_id, span_id = s.split(":", 1)
            return cls(trace_id, span_id) if trace_id else None
        except ValueError:
            return None

    def __repr__(self):
        return f"TraceContext({self.trace_id[:8]}…, {self.span_id[:8]}…)"


class _SpanHandle:
    """An OPEN span: children minted while it is ambient parent to it;
    ``finish`` (or context-manager exit) emits the completed record."""

    __slots__ = ("ctx", "name", "t0", "tags", "events", "component",
                 "_prev", "_done")

    def __init__(self, ctx: TraceContext, name: str, tags, component):
        self.ctx = ctx
        self.name = name
        self.t0 = time.time()
        self.tags = tags
        self.events: List[list] = []
        self.component = component
        self._prev = None
        self._done = False

    def event(self, name: str):
        self.events.append([time.time(), str(name)])

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        finish(self, status="error" if exc_type is not None else "ok")
        return False


class Tracer:
    """Per-process span sink: a bounded ring plus (optionally) a
    spill file for processes nobody can dial (worker processes)."""

    def __init__(self, capacity: int = 65536, component: str = "driver",
                 node: str = "", spill_dir: Optional[str] = None):
        self.component = component
        self.node = node
        self.pid = os.getpid()
        self._spans: "deque[tuple]" = deque(maxlen=max(int(capacity), 16))
        self.spans_recorded = 0
        # Separate locks for the span ring and the task-context map:
        # submit threads register contexts while completion/report
        # threads emit spans — one shared lock would serialize the two
        # hottest traced paths against each other.
        self._lock = threading.Lock()        # span ring
        self._ctx_lock = threading.Lock()    # task-context map
        # task_id bin -> TraceContext, bounded FIFO (the task-event
        # bridge resolves per-task contexts through this).
        self._task_ctx: Dict[bytes, TraceContext] = {}
        self._task_order: "deque[bytes]" = deque()
        self._spill_path: Optional[str] = None
        self._spill_file = None
        self._spill_lock = threading.Lock()
        self._spill_cap = self._spans.maxlen
        self._spilled = 0
        if spill_dir:
            try:
                os.makedirs(spill_dir, exist_ok=True)
                self._spill_path = os.path.join(
                    spill_dir,
                    f"spans-{self.pid}-{uuid.uuid4().hex[:8]}.jsonl")
            except OSError:
                self._spill_path = None

    # ------------------------------------------------------------ identity
    def set_identity(self, component: Optional[str] = None,
                     node: Optional[str] = None):
        if component is not None:
            self.component = component
        if node is not None:
            self.node = node

    # -------------------------------------------------------------- record
    # Spans live in the ring as TUPLES (no per-emit dict build, no
    # per-emit stringification, far less GC pressure on the hot path);
    # ``_as_dict`` renders them at the rare dump/spill boundary.
    def emit(self, trace_id: str, span_id: str, parent_id: str, name: str,
             t0: float, dur: float, status: str = "ok",
             component: Optional[str] = None,
             tags: Optional[Dict[str, Any]] = None,
             events: Optional[List[list]] = None) -> None:
        rec = (trace_id, span_id, parent_id, name, t0,
               dur if dur > 0.0 else 0.0, status,
               component or self.component, tags, events)
        with self._lock:
            self._spans.append(rec)
            self.spans_recorded += 1
        if self._spill_path is not None:
            self._spill(self._as_dict(rec))

    def _as_dict(self, rec: tuple) -> dict:
        tags, events = rec[8], rec[9]
        return {
            "trace_id": rec[0],
            "span_id": rec[1],
            "parent_id": rec[2],
            "name": rec[3],
            "t0": float(rec[4]),
            "dur": float(rec[5]),
            "status": rec[6],
            "component": rec[7],
            "pid": self.pid,
            "node": self.node,
            "tags": {str(k): str(v) for k, v in tags.items()}
            if tags else {},
            "events": [[float(ts), str(n)] for ts, n in events]
            if events else [],
        }

    def _spill(self, span: dict):
        line = json.dumps(span) + "\n"
        with self._spill_lock:
            try:
                if self._spill_file is None:
                    self._spill_file = open(  # noqa: SIM115 — long-lived
                        self._spill_path, "a", buffering=1)
                elif self._spilled >= self._spill_cap:
                    # Coarse ring: restart the file at the newest window
                    # so a long-lived traced worker's spill stays
                    # bounded (<= capacity spans on disk, same bound as
                    # the in-memory ring) instead of growing — and
                    # dump-side re-reads stay O(capacity), not O(run).
                    self._spill_file.close()
                    self._spill_file = open(  # noqa: SIM115 — long-lived
                        self._spill_path, "w", buffering=1)
                    self._spilled = 0
                self._spill_file.write(line)
                self._spilled += 1
            except OSError:
                self._spill_path = None  # disk gone: ring-only from here

    # ---------------------------------------------------------- task bridge
    def register_task(self, tid_bin: bytes, ctx: TraceContext):
        with self._ctx_lock:
            if tid_bin not in self._task_ctx:
                self._task_order.append(tid_bin)
            self._task_ctx[tid_bin] = ctx
            while len(self._task_order) > 65536:
                self._task_ctx.pop(self._task_order.popleft(), None)

    def task_context(self, tid_bin: bytes) -> Optional[TraceContext]:
        with self._ctx_lock:
            return self._task_ctx.get(tid_bin)

    # ---------------------------------------------------------------- read
    def dump(self, trace_id: Optional[str] = None,
             include_dir: bool = True) -> List[dict]:
        """This process's spans (ring + any spill files written by child
        worker processes into this process's trace dir)."""
        with self._lock:
            recs = list(self._spans)
        if trace_id:
            recs = [r for r in recs if r[0] == trace_id]
        spans = [self._as_dict(r) for r in recs]
        if include_dir:
            extra = _read_spill_dir(os.environ.get(ENV_DIR),
                                    exclude_pid=self.pid)
            if trace_id:
                extra = [s for s in extra
                         if s.get("trace_id") == trace_id]
            spans.extend(extra)
        return spans

    def trace_index(self, include_dir: bool = True) -> Dict[str, dict]:
        """Per-trace aggregates over the local ring (+ child spill
        files): the cluster trace INDEX input — O(traces) on the wire
        where a full ``dump`` ships O(spans) rendered dicts."""
        out: Dict[str, dict] = {}

        def add(tid, t0, status, comp, proc, name, parent):
            rec = out.get(tid)
            if rec is None:
                rec = out[tid] = {
                    "num_spans": 0, "first_t0": t0, "errors": 0,
                    "root": "", "pids": set(), "components": set()}
            rec["num_spans"] += 1
            rec["first_t0"] = min(rec["first_t0"], t0)
            if status == "error":
                rec["errors"] += 1
            if not parent:
                rec["root"] = name
            rec["pids"].add(proc)
            rec["components"].add(comp)

        with self._lock:
            recs = list(self._spans)
        # Process identity is node-qualified ("node:pid"): bare pids
        # from different hosts collide and would undercount when the
        # cluster index merges sources.
        self_proc = process_key(self.node, self.pid)
        for r in recs:
            add(r[0], float(r[4]), r[6], r[7], self_proc, r[3], r[2])
        if include_dir:
            for s in _read_spill_dir(os.environ.get(ENV_DIR),
                                     exclude_pid=self.pid):
                add(s.get("trace_id", ""), float(s.get("t0", 0.0)),
                    s.get("status", "ok"), s.get("component", ""),
                    process_key(s.get("node", ""), s.get("pid", 0)),
                    s.get("name", ""), s.get("parent_id", ""))
        for rec in out.values():
            rec["pids"] = sorted(rec["pids"])
            rec["components"] = sorted(rec["components"])
        return out


def process_key(node: str, pid) -> str:
    """Cluster-unique process identity for assembled views: pids alone
    collide across hosts (two nodes can both run a pid 1234)."""
    return f"{node or ''}:{pid}"


def _read_spill_dir(spill_dir: Optional[str],
                    exclude_pid: Optional[int] = None) -> List[dict]:
    """Spans spilled by (child) processes into ``spill_dir``. Files this
    process wrote itself are skipped — its ring already holds them."""
    if not spill_dir:
        return []
    out: List[dict] = []
    prefix_self = f"spans-{exclude_pid}-" if exclude_pid else None
    try:
        names = sorted(os.listdir(spill_dir))
    except OSError:
        return []
    for name in names:
        if not name.endswith(".jsonl") or not name.startswith("spans-"):
            continue
        if prefix_self and name.startswith(prefix_self):
            continue
        try:
            with open(os.path.join(spill_dir, name)) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        out.append(json.loads(line))
        except (OSError, ValueError):
            continue  # racing writer mid-line / rotated file
    return out


# ------------------------------------------------------------ installation
_install_lock = threading.Lock()


def _capacity() -> int:
    try:
        from ray_tpu._private.config import GlobalConfig

        return int(GlobalConfig.trace_max_spans)
    except Exception:  # noqa: BLE001 — config unavailable at bootstrap
        return 65536


def install(component: str = "driver", node: str = "",
            capacity: Optional[int] = None, spill: bool = False) -> Tracer:
    """Activate tracing process-wide (idempotent per process: a second
    install re-labels the existing tracer instead of dropping its
    ring). ``spill=True`` (worker processes — nothing can dial them)
    additionally appends finished spans to ``RAY_TPU_TRACE_DIR``; ring
    processes with a dialable ``trace_dump`` surface never spill."""
    global _TRACER
    with _install_lock:
        if _TRACER is not None:
            _TRACER.set_identity(component=component, node=node or None)
            return _TRACER
        _TRACER = Tracer(
            capacity=capacity if capacity is not None else _capacity(),
            component=component, node=node,
            spill_dir=os.environ.get(ENV_DIR) if spill else None)
        return _TRACER


def install_from_env(component: str = "driver",
                     spill: bool = False) -> Optional[Tracer]:
    raw = (os.environ.get(ENV_VAR) or "").strip().lower()
    if not raw or raw in ("0", "false", "off"):
        return None
    # Node identity injected by the hosting runtime (spawned worker
    # processes inherit it): without it, spans from same-pid processes
    # on different hosts collapse in assembled views.
    return install(component=component,
                   node=os.environ.get(ENV_NODE, ""), spill=spill)


def uninstall() -> None:
    global _TRACER
    with _install_lock:
        _TRACER = None


def tracer() -> Optional[Tracer]:
    return _TRACER


def active() -> bool:
    return _TRACER is not None


# ------------------------------------------------------------- span API
# Span ids are (random per-process prefix) + (counter): unique across
# the cluster w.h.p. at ~50ns per id — an os.urandom syscall per span
# would dominate the whole emit cost on the fan-out hot path.
_ID_PREFIX = os.urandom(4).hex()
_ids = itertools.count(1)


def _new_id() -> str:
    return f"{_ID_PREFIX}{next(_ids):08x}"


def new_trace() -> Optional[TraceContext]:
    if _TRACER is None:
        return None
    return TraceContext(uuid.uuid4().hex, "")


def current_context() -> Optional[TraceContext]:
    if _TRACER is None:
        return None
    return getattr(_tls, "ctx", None)


class use_context:
    """Make ``ctx`` the ambient parent for this thread (no-op when
    tracing is off or ``ctx`` is None)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx
        self._prev = None

    def __enter__(self):
        if _TRACER is not None and self._ctx is not None:
            self._prev = getattr(_tls, "ctx", None)
            _tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        if _TRACER is not None and self._ctx is not None:
            _tls.ctx = self._prev
        return False


def begin(name: str, parent: Optional[TraceContext] = None,
          component: Optional[str] = None,
          **tags) -> Optional[_SpanHandle]:
    """Open a span (ambient parent unless ``parent`` given; a fresh
    trace when neither exists) and make it the thread's ambient
    context. Returns None when tracing is off — ``finish`` accepts
    None, so call sites stay branch-free."""
    t = _TRACER
    if t is None:
        return None
    if parent is None:
        parent = getattr(_tls, "ctx", None)
    if parent is None:
        ctx = TraceContext(uuid.uuid4().hex, _new_id())
        parent_id = ""
    else:
        ctx = TraceContext(parent.trace_id, _new_id())
        parent_id = parent.span_id
    handle = _SpanHandle(ctx, name, dict(tags), component)
    handle.tags["_parent"] = parent_id  # carried to finish
    handle._prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    return handle


def finish(handle: Optional[_SpanHandle], status: str = "ok",
           **tags) -> None:
    t = _TRACER
    if t is None or handle is None or handle._done:
        return
    handle._done = True
    _tls.ctx = handle._prev
    all_tags = dict(handle.tags)
    parent_id = all_tags.pop("_parent", "")
    all_tags.update(tags)
    t.emit(handle.ctx.trace_id, handle.ctx.span_id, parent_id,
           handle.name, handle.t0, time.time() - handle.t0,
           status=status, component=handle.component, tags=all_tags,
           events=handle.events)


def start_span(name: str, parent: Optional[TraceContext] = None,
               **tags):
    """Context-manager span: ``with tracing.start_span("x") as s: ...``
    (``s`` is None when tracing is off)."""
    handle = begin(name, parent=parent, **tags)
    if handle is None:
        return _NULL_SPAN
    return handle


class _NullSpan:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def event(name: str, ctx: Optional[TraceContext] = None,
          component: Optional[str] = None, **tags) -> None:
    """A point-in-time record: a zero-duration span under ``ctx`` (or
    the ambient context). Dropped silently without a context — events
    outside any trace are noise, not data."""
    t = _TRACER
    if t is None:
        return
    if ctx is None:
        ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return
    now = time.time()
    t.emit(ctx.trace_id, _new_id(), ctx.span_id, name, now, 0.0,
           component=component, tags=tags)


# --------------------------------------------------------------- wire form
def inject(ctx: Optional[TraceContext] = None
           ) -> Optional[Tuple[str, str]]:
    """Wire form of a context: ``(trace_id, span_id)`` or None when
    tracing is off / no context exists. Payload builders add a key only
    on a non-None return — off means ZERO extra bytes on the wire."""
    if _TRACER is None:
        return None
    if ctx is None:
        ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return None
    return (ctx.trace_id, ctx.span_id)


def extract(wire: Any) -> Optional[TraceContext]:
    """Inverse of :func:`inject`; tolerant of msgpack'd tuples/lists
    and byte strings. None when tracing is off here (an armed sender
    to an unarmed receiver costs the receiver one branch)."""
    if _TRACER is None or wire is None:
        return None
    try:
        trace_id, span_id = wire
        if isinstance(trace_id, bytes):
            trace_id = trace_id.decode()
        if isinstance(span_id, bytes):
            span_id = span_id.decode()
        return TraceContext(str(trace_id), str(span_id))
    except (TypeError, ValueError):
        return None


# ------------------------------------------------------- task-event bridge
def register_task(tid_bin: bytes, wire_or_ctx: Any) -> None:
    """Associate a task id with a trace context so the task-event
    bridge (scheduler/actor state transitions) emits spans for it."""
    t = _TRACER
    if t is None or wire_or_ctx is None:
        return
    ctx = wire_or_ctx if isinstance(wire_or_ctx, TraceContext) \
        else extract(wire_or_ctx)
    if ctx is not None:
        t.register_task(bytes(tid_bin), ctx)


def task_context(tid_bin: bytes) -> Optional[TraceContext]:
    t = _TRACER
    if t is None:
        return None
    return t.task_context(bytes(tid_bin))


def on_task_event(task_id, state: str, name: str, prev) -> None:
    """Called by ``TaskEventBuffer.record`` (under no lock) for task
    state transitions. Only the hops that matter become spans — entry
    into RUNNING closes a ``task.queue`` span (time spent pending) and
    a terminal state closes ``task.exec`` — so a traced task costs two
    emits on its executing runtime, not one per bookkeeping state."""
    t = _TRACER
    if t is None:
        return
    emit_queue = state == "RUNNING" and prev is not None
    emit_exec = state in _TERMINAL
    if not (emit_queue or emit_exec):
        return
    try:
        tid_bin = task_id.binary()
    except AttributeError:
        return
    ctx = t.task_context(tid_bin)
    if ctx is None:
        return
    now = time.time()
    if emit_queue:
        t.emit(ctx.trace_id, _new_id(), ctx.span_id, "task.queue",
               prev.timestamp, now - prev.timestamp,
               tags={"task": name})
        return
    if prev is None:
        # Bare terminal record (no prior state in this buffer): a
        # zero-duration marker still shows the completion happened.
        t.emit(ctx.trace_id, _new_id(), ctx.span_id,
               f"task.{state.lower()}", now, 0.0, tags={"task": name})
        return
    status = "error" if state == "FAILED" else "ok"
    t.emit(ctx.trace_id, _new_id(), ctx.span_id, "task.exec",
           prev.timestamp, now - prev.timestamp, status=status,
           tags={"task": name})


# ------------------------------------------------------- cold-start chain
# One-slot stash: the request/reconcile thread that discovers missing
# capacity parks its context here; the autoscaler's launch loop adopts
# it so the node launch (and, via RAY_TPU_TRACE_PARENT, the launched
# daemon's init + the head's join record) lands in the SAME trace.
_cold_start_lock = threading.Lock()
_cold_start_ctx: Optional[Tuple[TraceContext, float]] = None


def _cold_start_window_s() -> float:
    try:
        from ray_tpu._private.config import GlobalConfig

        return float(GlobalConfig.trace_cold_start_window_s)
    except Exception:  # noqa: BLE001 — config unavailable at bootstrap
        return 180.0


def stash_cold_start(ctx: Optional[TraceContext] = None,
                     deadline: Optional[float] = None) -> None:
    """Park ``ctx`` (or the ambient context) for the next node launch.
    ``deadline`` (monotonic) lets a failed launch RE-park the context
    it took without resetting the expiry window — repeated launch
    failures must not keep a dead trace adoptable forever."""
    global _cold_start_ctx
    if _TRACER is None:
        return
    if ctx is None:
        ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return
    if deadline is None:
        deadline = time.monotonic() + _cold_start_window_s()
    with _cold_start_lock:
        _cold_start_ctx = (ctx, deadline)


def clear_cold_start(ctx: Optional[TraceContext]) -> None:
    """Drop the stash iff it still holds ``ctx``'s trace: the waker's
    exit path for requests satisfied WITHOUT a node launch — otherwise
    the next unrelated launch inside the cold-start window would adopt
    this long-finished context."""
    global _cold_start_ctx
    if _TRACER is None or ctx is None:
        return
    with _cold_start_lock:
        if (_cold_start_ctx is not None
                and _cold_start_ctx[0].trace_id == ctx.trace_id):
            _cold_start_ctx = None


def take_cold_start() -> Optional[TraceContext]:
    entry = take_cold_start_timed()
    return entry[0] if entry else None


def take_cold_start_timed() -> Optional[Tuple[TraceContext, float]]:
    """:func:`take_cold_start` plus the stash deadline, for callers
    that may re-park the context after a failed launch (pass the
    deadline back to :func:`stash_cold_start` so the window keeps
    counting from the ORIGINAL stash)."""
    global _cold_start_ctx
    if _TRACER is None:
        return None
    with _cold_start_lock:
        stashed, _cold_start_ctx = _cold_start_ctx, None
    if stashed is None:
        return None
    ctx, deadline = stashed
    # Same guard as RAY_TPU_TRACE_PARENT's cold-start window: a stash
    # nobody consumed (capacity satisfied without a launch) must not
    # attach a later unrelated scale-up to a long-finished trace.
    if time.monotonic() > deadline:
        return None
    return (ctx, deadline)


def encode_cold_start_parent(ctx: TraceContext) -> str:
    """ENV_PARENT wire form with the cold-start EXPIRY baked into the
    value (``trace_id:span_id:expires_epoch``): env copies outlive the
    launch — pooled worker processes inherit the variable and are
    reused for hours — so the window must ride the value itself, not
    just the hosting daemon's environment."""
    return (f"{ctx.trace_id}:{ctx.span_id}:"
            f"{time.time() + _cold_start_window_s():.0f}")


def cold_start_parent() -> Optional[TraceContext]:
    """The trace context a PARENT process injected into this process's
    environment (``RAY_TPU_TRACE_PARENT=<trace_id>:<span_id>[:expires]``)
    — the launched node daemon / spawned worker end of the cold-start
    chain. A value past its baked-in expiry returns None: a reused
    worker process leased for a later unrelated scale-up must not
    parent its replica init into a long-finished trace. (The hosting
    daemon also drops the variable from its own environment once the
    window passes.)"""
    if _TRACER is None:
        return None
    raw = os.environ.get(ENV_PARENT, "")
    if not raw:
        return None
    parts = raw.split(":")
    if len(parts) >= 3:
        try:
            if time.time() > float(parts[2]):
                return None
        except ValueError:
            pass
        return TraceContext(parts[0], parts[1]) if parts[0] else None
    return TraceContext.decode(raw)


# ----------------------------------------------------------------- reading
def local_spans(trace_id: Optional[str] = None) -> List[dict]:
    t = _TRACER
    if t is None:
        return []
    return t.dump(trace_id=trace_id)


def chrome_trace(spans: List[dict]) -> List[dict]:
    """Chrome-tracing JSON (``chrome://tracing`` / Perfetto): one "X"
    event per span, grouped by process (pid) and component."""
    out = []
    for s in spans:
        out.append({
            "name": s["name"],
            "cat": s.get("component", "span"),
            "ph": "X",
            "ts": s["t0"] * 1e6,
            "dur": max(s["dur"] * 1e6, 1.0),
            "pid": s.get("pid", 0),
            "tid": s.get("component", ""),
            "args": {
                "trace_id": s["trace_id"],
                "span_id": s["span_id"],
                "parent_id": s.get("parent_id", ""),
                "status": s.get("status", "ok"),
                "node": s.get("node", ""),
                **s.get("tags", {}),
            },
        })
        for ts, name in s.get("events", []):
            out.append({
                "name": name, "cat": "event", "ph": "i",
                "ts": ts * 1e6, "pid": s.get("pid", 0),
                "tid": s.get("component", ""), "s": "p",
                "args": {"trace_id": s["trace_id"]},
            })
    return out
