"""Cluster-wide actor placement: actors hosted on node daemons.

Rebuild of the reference's GCS actor management path (reference roles:
GcsActorManager / GcsActorScheduler placing actors on raylets, with
direct core-worker -> actor RPC for method calls — SURVEY §2.1, §3.3
[unverified; reference mount empty]). TPU-first shape:

- **Placement** is a driver-side decision (``RemoteRouter.place_actor``)
  informed by head membership: resources / NodeAffinity / SPREAD /
  thin-client, the same policy family as the task router.
- **Creation and method calls go direct-to-node** over the node's
  authenticated server (the object-server transport with an ``actor_op``
  handler), falling back to a head-relayed ``actor_push`` when the node
  is not directly dialable. The head never sits in the call path.
- **Results stay on the node**: the host announces the return ids and
  sends one tiny ``task_done`` through the head; the calling driver
  pulls the bytes peer-to-peer on demand (same plane as task results).
- **Node death**: the owning driver's router watcher fails in-flight
  calls with ``ActorDiedError`` and, within ``max_restarts`` budget,
  re-creates the actor with FRESH state on a surviving feasible node,
  updating the head's placement directory so named lookups and borrowed
  handles re-resolve.
- **Driver death**: the host kills actors whose owning driver the head
  declared dead (``lifetime="detached"`` opts out).
"""

from __future__ import annotations

import pickle
import queue
import threading
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

from ray_tpu._private.ids import ActorID, ObjectID, TaskID
from ray_tpu._private.log import get_logger
from ray_tpu._private.object_server import PeerUnreachableError
from ray_tpu._private import tracing
from ray_tpu._private.serialization import SerializedObject
from ray_tpu.exceptions import ActorDiedError, RayTaskError

log = get_logger(__name__)

_STOP = object()


# --------------------------------------------------------------- arg wiring
def wire_arg(router, v):
    """Driver-side wire form of one argument: plain values inline
    (serialized), refs whose bytes live on a node travel as pull-refs
    the host resolves node-side (the driver stays out of the data
    path). Waits for ref deps to be produced first."""
    from ray_tpu._private.worker import ObjectRef

    ctx = router.worker.serialization_context
    if not isinstance(v, ObjectRef):
        return ("v", ctx.serialize(v).to_bytes())
    router._await_dep(v.object_id)
    ob = v.object_id.binary()
    with router._lock:
        owner = router._oid_owner.get(ob)
    if owner is not None and router._client_alive(owner):
        return ("r", ob)
    value = router.worker.get_object(v)
    return ("v", ctx.serialize(value).to_bytes())


def unwire_arg(worker, head, wired, owner=None):
    """Host-side inverse: deserialize an inline value, or resolve a
    ref's bytes through its OWNER (the calling driver — its router
    tracks the holder; ``owner`` = (owner_id, addr) from the actor-op
    payload), with the head's fallback directory behind it."""
    kind, data = wired
    if kind == "v":
        return worker.serialization_context.deserialize(
            SerializedObject.from_bytes(bytes(data)))
    oid = ObjectID(bytes(data))
    if not worker.store.is_ready(oid):
        resolver = getattr(worker, "owner_resolver", None)
        if resolver is not None:
            # Owner tuples are (owner_id, addr) project-wide.
            owner_id = owner[0] if owner else None
            owner_addr = tuple(owner[1]) if owner and owner[1] else None
            resolver.resolve(oid.binary(), owner_addr, owner_id)
        else:  # no resolver (bare runtime): legacy head-directory pull
            raw = head.object_pull(oid.binary())
            if raw is None:
                raise ValueError(
                    f"pull-ref {oid.hex()[:16]}… has no live owner")
            worker.store.put(oid, SerializedObject.from_bytes(raw))
    return worker.serialization_context.deserialize(worker.store.get(oid))


def _node_addr(node: dict) -> Optional[tuple]:
    addr = (node.get("status") or {}).get("_peer_addr")
    return (str(addr[0]), int(addr[1])) if addr else None


# ------------------------------------------------------- driver-side runtime
class RemoteActorRuntime:
    """Driver-side stand-in for an actor hosted on a node daemon.

    Duck-types the ``_ActorRuntime`` surface ``ActorHandle`` needs
    (``submit``/``dead``/``cls``/``terminate``/``join``), so the public
    handle type is one and the same for local and cluster actors.
    """

    is_remote = True

    def __init__(self, worker, actor_id: ActorID, cls, init_args,
                 init_kwargs, *, node: Optional[dict],
                 max_restarts: int = 0, max_concurrency=None,
                 actor_name: Optional[str] = None,
                 opts: Optional[dict] = None,
                 borrower: bool = False,
                 node_record: Optional[dict] = None,
                 registered_name: Optional[tuple] = None):
        import cloudpickle

        self.worker = worker
        self.head = worker.head_client
        self.router = worker.remote_router
        self.actor_id = actor_id
        self.cls = cls
        self.class_name = getattr(cls, "__name__", None) or (
            (node_record or {}).get("class_name") or "Actor")
        self.actor_name = actor_name
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.opts = dict(opts or {})
        self.max_restarts = int(max_restarts or 0)
        self.max_concurrency = max_concurrency
        self.restarts_used = 0
        self.dead = False
        self.death_cause: Optional[str] = None
        self.borrower = borrower
        self.incarnation = 0
        self.pid: Optional[int] = None
        self._lock = threading.Lock()
        self._seq = 0
        # Task ids must be caller-unique: the owner and every borrower
        # mint ids for the same actor, so derive from a per-runtime
        # random base instead of (actor_id, seq).
        self._task_base = TaskID.from_random()
        self._inflight: Dict[TaskID, List[ObjectID]] = {}
        self._relocate_misses = 0
        if registered_name is not None:
            # Known BEFORE the async create dispatches, so a creation
            # failure can release the cluster-wide name (no race with
            # the caller assigning it after construction).
            self._registered_name = registered_name
        if borrower:
            self._cls_bytes = (node_record or {}).get("cls") or b""
            self.node_client = node_record["node"]
            self.node_addr = tuple(node_record["addr"]) \
                if node_record.get("addr") else None
        else:
            self._cls_bytes = cloudpickle.dumps(cls)
            self.node_client = node["client_id"]
            self.node_addr = _node_addr(node)
        # One dispatch thread: creation and every method call ship in
        # submission order; ref-arg waits never block the caller.
        self._dispatch = ThreadPoolExecutor(
            max_workers=1,
            thread_name_prefix=f"remote-actor-{self.class_name}")
        if not borrower:
            self._dispatch.submit(self._do_create)
        self.router.watch_remote_actor(self)

    # ------------------------------------------------------------- transport
    def _node_call(self, payload: bytes):
        if self.node_addr is not None:
            try:
                return self.head.node_call(
                    self.node_addr, ("actor_op", payload))
            except PeerUnreachableError:
                pass  # fall back to the head-relayed control path
        return self.head.actor_push(self.node_client, payload)

    # -------------------------------------------------------------- creation
    def _do_create(self):
        try:
            wired_args = [wire_arg(self.router, a) for a in self.init_args]
            wired_kwargs = {k: wire_arg(self.router, v)
                            for k, v in self.init_kwargs.items()}
            payload = pickle.dumps({
                "op": "create",
                "actor_id": self.actor_id.binary(),
                "cls": self._cls_bytes,
                "args": wired_args,
                "kwargs": wired_kwargs,
                "max_concurrency": self.max_concurrency,
                "max_restarts": self.max_restarts,
                "runtime_target": self.opts.get("runtime"),
                "driver_id": self.head.client_id,
                "driver_addr": list(self.head._object_server.address),
                "name": self.class_name,
                "detached": self.opts.get("lifetime") == "detached",
            }, protocol=5)
            reply = self._node_call(payload)
            if isinstance(reply, dict):
                self.pid = reply.get("pid")
            self.head.actor_place(self.actor_id.binary(), {
                "node": self.node_client,
                "driver": self.head.client_id,
                "cls": self._cls_bytes,
                "class_name": self.class_name,
                "detached": self.opts.get("lifetime") == "detached",
            })
        except BaseException as exc:  # noqa: BLE001 — creation boundary
            # _die (not a bare flag): the cluster-wide name and any
            # placement record must release, or retries fail "name
            # already taken" for the life of this driver.
            self._die(f"remote actor creation failed: {exc!r}")

    # ------------------------------------------------------------ submission
    def submit(self, method_name: str, args, kwargs, num_returns: int,
               name: str):
        from ray_tpu._private.worker import ObjectRef

        with self._lock:
            self._seq += 1
            task_id = TaskID.of(self._task_base, self._seq)
        return_ids = [ObjectID.for_task_return(task_id, i)
                      for i in range(num_returns)]
        refs = [ObjectRef(oid) for oid in return_ids]
        if self.dead:
            err = ActorDiedError(self.actor_id,
                                 self.death_cause or "actor is dead")
            for oid in return_ids:
                self.worker.store.put_error(oid, err)
            return refs
        self.router.register_external(task_id, self.node_client)
        with self._lock:
            self._inflight[task_id] = list(return_ids)
        trace_wire = tracing.inject()  # caller thread's ambient context
        if trace_wire is not None:
            tracing.register_task(task_id.binary(), trace_wire)
        self.worker.task_events.record(task_id, "PENDING_ACTOR_TASK",
                                       name=name)
        self._dispatch.submit(self._do_submit, task_id, method_name,
                              args, kwargs, return_ids, name, trace_wire)
        return refs

    def _do_submit(self, task_id: TaskID, method_name: str, args, kwargs,
                   return_ids, name: str, trace_wire=None):
        if self.dead:
            self._fail(return_ids, ActorDiedError(
                self.actor_id, self.death_cause or "actor is dead"))
            return
        try:
            wired_args = [wire_arg(self.router, a) for a in args]
            wired_kwargs = {k: wire_arg(self.router, v)
                            for k, v in kwargs.items()}
            fields = {
                "op": "submit",
                "actor_id": self.actor_id.binary(),
                "incarnation": self.incarnation,
                "method": method_name,
                "args": wired_args,
                "kwargs": wired_kwargs,
                "return_ids": [o.binary() for o in return_ids],
                "task_id": task_id.binary(),
                "name": name,
                "driver_id": self.head.client_id,
                # Owner identity: the host resolves arg locations and
                # pushes completion reports owner-direct with this.
                "driver_addr": list(self.head._object_server.address),
            }
            if trace_wire is not None:
                # actor_op hop carries the caller's trace context: the
                # hosting node's task-event bridge emits its spans.
                fields["trace"] = trace_wire
            payload = pickle.dumps(fields, protocol=5)
            self._node_call(payload)
        except BaseException as exc:  # noqa: BLE001 — dispatch boundary
            if isinstance(exc, (ActorDiedError, RayTaskError)):
                self._fail(return_ids, exc)
            else:
                self._fail(return_ids, ActorDiedError(
                    self.actor_id,
                    f"could not reach actor's node: {exc}"))

    def _fail(self, return_ids, err: BaseException):
        for oid in return_ids:
            if not self.worker.store.is_ready(oid):
                self.worker.store.put_error(oid, err)

    # --------------------------------------------------------- node watching
    def check_node(self, alive: set):
        """Called from the router's watch loop with the alive node set."""
        if self.dead:
            return
        self._prune_inflight()
        if self.node_client in alive:
            self._relocate_misses = 0
            return
        self._on_node_dead()

    def _prune_inflight(self):
        with self._lock:
            tids = list(self._inflight)
        for tid in tids:
            ev = self.router._done.get(tid)
            if ev is not None and ev.is_set():
                with self._lock:
                    self._inflight.pop(tid, None)

    def _on_node_dead(self):
        err = ActorDiedError(
            self.actor_id,
            f"node {self.node_client!r} hosting this actor died")
        with self._lock:
            inflight, self._inflight = dict(self._inflight), {}
        for oids in inflight.values():
            self._fail(oids, err)
        if self.borrower:
            # The owner may be re-placing the actor: re-resolve through
            # the placement directory for a while before declaring it
            # dead.
            try:
                rec = self.head.actor_locate(self.actor_id.binary())
            except Exception:  # noqa: BLE001 — head hiccup: retry later
                rec = None
            if rec is not None and rec.get("alive") \
                    and rec.get("node") != self.node_client:
                self.node_client = rec["node"]
                self.node_addr = tuple(rec["addr"]) if rec.get("addr") \
                    else None
                self._relocate_misses = 0
                return
            self._relocate_misses += 1
            if self._relocate_misses > 20:  # ~10 s of watcher ticks
                self.dead = True
                self.death_cause = str(err)
            return
        if self.restarts_used >= self.max_restarts:
            self._die(str(err))
            return
        node = self._choose_restart_node()
        if node is None:
            self._die(f"{err} and no surviving feasible node to restart "
                      f"on")
            return
        self.restarts_used += 1
        self.incarnation += 1
        self.node_client = node["client_id"]
        self.node_addr = _node_addr(node)
        # Fresh state on the new node (reference restart semantics).
        self._dispatch.submit(self._do_create)

    def _choose_restart_node(self) -> Optional[dict]:
        demand = self.router.actor_demand(self.opts)
        nodes = [n for n in self.router.nodes(refresh=True)
                 if n.get("alive") and n["client_id"] != self.node_client]
        feasible = [n for n in nodes if self.router._fits(n, demand)]
        if not feasible:
            return None
        return min(feasible, key=self.router._actor_load)

    def _die(self, cause: str):
        self.dead = True
        self.death_cause = cause
        try:
            self.head.actor_unplace(self.actor_id.binary())
        except Exception:  # noqa: BLE001 — head gone
            pass
        reg = getattr(self, "_registered_name", None)
        if reg is not None:
            try:
                self.head.actor_deregister(*reg)
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------------- lifecycle
    def terminate(self, no_restart: bool = True):
        payload = pickle.dumps({
            "op": "kill",
            "actor_id": self.actor_id.binary(),
            "no_restart": bool(no_restart),
        }, protocol=5)
        if self.dead and no_restart:
            # Already marked dead DRIVER-side — but death marking is a
            # liveness inference (node briefly absent from membership),
            # not ground truth. Still push the node-side kill: a
            # false-positive death would otherwise orphan the hosted
            # actor on a live daemon forever (it counts as load, so an
            # autoscaler never reaps the node). Idempotent: a truly
            # dead node/actor ignores it.
            try:
                self._dispatch.submit(self._kill_quietly, payload)
            except RuntimeError:  # dispatch already shut down
                pass
            return
        if no_restart:
            err = ActorDiedError(self.actor_id, "killed via ray_tpu.kill()")
            with self._lock:
                inflight, self._inflight = dict(self._inflight), {}
            self.dead = True
            self.death_cause = "killed via ray_tpu.kill()"
            for oids in inflight.values():
                self._fail(oids, err)
            self._dispatch.submit(self._kill_quietly, payload)
            if not self.borrower:
                self._die(self.death_cause)
        else:
            # Node-local restart with fresh state: the host's runtime
            # respawns the worker process, consuming ITS restart budget —
            # mirrors the in-driver terminate(no_restart=False) path.
            self._dispatch.submit(self._kill_quietly, payload)

    def _kill_quietly(self, payload: bytes):
        try:
            self._node_call(payload)
        except Exception:  # noqa: BLE001 — node gone: nothing to kill
            pass

    def join(self, timeout=None):
        self._dispatch.shutdown(wait=False)


def resolve_or_borrow(worker, actor_id: ActorID):
    """One-stop runtime resolution: this driver's own runtime if it has
    one, else a borrower runtime from the placement directory (cached in
    ``worker.actors`` so repeated resolutions reuse one runtime)."""
    runtime = worker.actors.get(actor_id)
    if runtime is not None:
        return runtime
    if worker.head_client is None:
        return None
    runtime = borrow_placed_actor(worker, actor_id)
    if runtime is not None:
        worker.actors[actor_id] = runtime
    return runtime


def borrow_placed_actor(worker, actor_id: ActorID):
    """Resolve a cluster-placed actor into a borrower runtime (calls go
    direct to the hosting node; no lifetime ownership). None when the
    placement directory has no live record."""
    import cloudpickle

    head = worker.head_client
    if head is None or worker.remote_router is None:
        return None
    try:
        rec = head.actor_locate(actor_id.binary())
    except Exception:  # noqa: BLE001 — head unreachable
        return None
    if rec is None or not rec.get("alive"):
        return None
    cls = None
    if rec.get("cls"):
        try:
            cls = cloudpickle.loads(bytes(rec["cls"]))
        except Exception:  # noqa: BLE001 — class not importable here:
            cls = None  # the handle skips method validation
    return RemoteActorRuntime(
        worker, actor_id, cls, (), {},
        node=None, borrower=True, node_record=rec)


# --------------------------------------------------------- node-side hosting
class ActorHost:
    """Daemon-side end of the cluster actor plane: hosts actors in the
    node's local runtime (``_ActorRuntime`` — worker processes, node-
    local restarts) and serves create/submit/kill from remote drivers,
    direct or head-relayed."""

    def __init__(self, worker, head, on_owner_seen=None):
        self.worker = worker
        self.head = head
        # Hosting daemon's hook: actor ops carry the calling driver's
        # report address too, so actor-only nodes still learn where
        # their tail task events ship.
        self._on_owner_seen = on_owner_seen
        self._lock = threading.Lock()
        self._queues: Dict[bytes, "queue.Queue"] = {}
        self._owners: Dict[bytes, str] = {}     # actor_bin -> driver client
        self._detached: set = set()
        # Results pinned against store GC until the caller pulls them.
        # Lifecycle is time-based (callers pull promptly — ensure_local
        # fires on the task_done event), with a count cap as the memory
        # backstop; a FIFO-only cap could evict a not-yet-pulled result.
        from ray_tpu._private.config import GlobalConfig

        self._pinned: "OrderedDict[bytes, tuple]" = OrderedDict()
        # Coupled to the router's bounded pull-retry window: pins must
        # outlive the retries or gets fail before the bytes expire.
        self._pin_ttl_s = GlobalConfig.external_pull_ttl_s
        self._pin_cap = 16384
        head._object_server.handlers["actor_op"] = self._on_direct
        head.handlers["actor_push"] = self._on_push
        self._sub = head.subscribe("ray_tpu:node_events",
                                   self._on_node_event)

    # --------------------------------------------------------------- ingress
    def _on_direct(self, msg: tuple):
        return self.handle(pickle.loads(bytes(msg[1])))

    def _on_push(self, event: tuple):
        return self.handle(pickle.loads(bytes(event[1])))

    def handle(self, p: dict):
        if self._on_owner_seen is not None and p.get("driver_addr"):
            self._on_owner_seen(tuple(p["driver_addr"]),
                                p.get("driver_id"))
        op = p["op"]
        if op == "create":
            return self._create(p)
        if op == "submit":
            return self._enqueue_submit(p)
        if op == "kill":
            return self._kill(p)
        raise ValueError(f"unknown actor op {op!r}")

    # ---------------------------------------------------------------- create
    def _create(self, p: dict):
        import cloudpickle

        from ray_tpu.actor import _ActorRuntime

        aid = ActorID(bytes(p["actor_id"]))
        cls = cloudpickle.loads(bytes(p["cls"]))
        owner = (p.get("driver_id"), p.get("driver_addr"))
        args = tuple(unwire_arg(self.worker, self.head, a, owner)
                     for a in p["args"])
        kwargs = {k: unwire_arg(self.worker, self.head, v, owner)
                  for k, v in p["kwargs"].items()}
        runtime = _ActorRuntime(
            aid, cls, args, kwargs,
            max_concurrency=p.get("max_concurrency"),
            max_restarts=int(p.get("max_restarts") or 0),
            name=p.get("name") or cls.__name__,
            actor_name=None,
            runtime_target=p.get("runtime_target"),
        )
        abin = aid.binary()
        with self._lock:
            old_q = self._queues.pop(abin, None)
            self.worker.actors[aid] = runtime
            self._owners[abin] = p["driver_id"]
            if p.get("detached"):
                self._detached.add(abin)
            q: "queue.Queue" = queue.Queue()
            self._queues[abin] = q
        if old_q is not None:
            old_q.put(_STOP)
        threading.Thread(
            target=self._dispatch_loop, args=(abin, q), daemon=True,
            name=f"actor-host-{p.get('name')}").start()
        return {"pid": runtime.pid}

    # ---------------------------------------------------------------- submit
    def _enqueue_submit(self, p: dict):
        abin = bytes(p["actor_id"])
        with self._lock:
            q = self._queues.get(abin)
        if q is None:
            raise ActorDiedError(
                ActorID(abin), "no such actor on this node")
        q.put(p)
        return "accepted"

    def _dispatch_loop(self, abin: bytes, q: "queue.Queue"):
        """Per-actor dispatcher: resolves args (which may pull bytes from
        other nodes) and submits to the runtime IN ARRIVAL ORDER, without
        blocking the connection thread."""
        while True:
            p = q.get()
            if p is _STOP:
                return
            try:
                self._dispatch_submit(p)
            except Exception as exc:  # errors already materialized
                log.debug("actor submit dispatch failed (error already "
                          "materialized to its refs): %r", exc)

    def _dispatch_submit(self, p: dict):
        aid = ActorID(bytes(p["actor_id"]))
        return_ids = [ObjectID(bytes(b)) for b in p["return_ids"]]
        driver_id = p["driver_id"]
        runtime = self.worker.actors.get(aid)
        try:
            if runtime is None or runtime.dead:
                raise ActorDiedError(
                    aid, getattr(runtime, "death_cause", None)
                    or "actor is not alive on this node")
            owner = (p.get("driver_id"), p.get("driver_addr"))
            args = tuple(unwire_arg(self.worker, self.head, a, owner)
                         for a in p["args"])
            kwargs = {k: unwire_arg(self.worker, self.head, v, owner)
                      for k, v in p["kwargs"].items()}
            if tracing._TRACER is not None and p.get("trace") is not None:
                # The caller's context rode the actor_op payload: this
                # node's task-event bridge emits the call's spans.
                tracing.register_task(bytes(p["task_id"]), p["trace"])
            refs = runtime.submit_prepared(
                p["method"], args, kwargs, return_ids, p["name"])
            self._pin(refs)
        except BaseException as exc:  # noqa: BLE001 — materialize + report
            err = exc if isinstance(exc, (ActorDiedError, RayTaskError)) \
                else RayTaskError.from_exception(p["name"], exc)
            for oid in return_ids:
                if not self.worker.store.is_ready(oid):
                    self.worker.store.put_error(oid, err)
        threading.Thread(
            target=self._report,
            args=(driver_id, p.get("driver_addr"), bytes(p["task_id"]),
                  return_ids),
            daemon=True, name="actor-host-report").start()

    def _pin(self, refs):
        import time as _time

        now = _time.monotonic()
        with self._lock:
            for r in refs:
                self._pinned[r.object_id.binary()] = (r, now)
            # Reap expired pins first; the cap only guards runaway load.
            while self._pinned:
                _, (_, ts) = next(iter(self._pinned.items()))
                if now - ts > self._pin_ttl_s \
                        or len(self._pinned) > self._pin_cap:
                    self._pinned.popitem(last=False)
                else:
                    break

    def _report(self, driver_id: str, driver_addr, task_bin: bytes,
                return_ids):
        """Send the completion event to the OWNING driver — direct to
        its object server first (the report carries the locations; the
        owner's directory serves later peer queries, the head stays
        untouched), head relay as the fallback (which records the
        locations server-side for the relayed consumer's pulls). Like
        the task plane's reports, small results ride INLINE and errors
        cross as pickled exceptions (no pullable bytes exist for them);
        big results stay pinned here and the driver pulls p2p on
        demand."""
        from ray_tpu._private.node_daemon import completion_fields
        from ray_tpu._private.object_server import PeerUnreachableError

        store = self.worker.store
        store.wait(return_ids, len(return_ids), timeout=None)
        sizes, errs, inline = completion_fields(
            store, return_ids, "actor task")
        oid_bins = [o.binary() for o in return_ids]
        done = pickle.dumps({
            "task_id": task_bin,
            "oid_bins": oid_bins,
            "node_client": self.head.client_id,
            "sizes": sizes,
            "errs": errs,
            "inline": inline,
        }, protocol=5)
        from ray_tpu._private.config import GlobalConfig

        if GlobalConfig.ownership_directory and driver_addr:
            try:
                self.head._peers.call(tuple(driver_addr),
                                      ("task_done", done))
                return
            except Exception as exc:  # noqa: BLE001 — NAT'd driver OR a
                # driver-side handler error: either way the relay below
                # must still record locations + deliver the completion.
                log.debug("direct actor task_done push failed; taking "
                          "the head relay: %r", exc)
        try:
            # Relay fallback: errored oids announce too, so a remote
            # consumer's pull raises the typed error instead of
            # retrying to a timeout.
            self.head.object_announce_many(oid_bins)
            self.head.task_done(driver_id, oid_bins, done)
        except Exception:  # noqa: BLE001 — driver/head gone: results stay
            pass

    # ------------------------------------------------------------------ kill
    def _kill(self, p: dict):
        aid = ActorID(bytes(p["actor_id"]))
        abin = aid.binary()
        no_restart = bool(p.get("no_restart", True))
        runtime = self.worker.actors.get(aid)
        if runtime is None:
            return None
        runtime.terminate(no_restart=no_restart)
        if no_restart:
            with self._lock:
                q = self._queues.pop(abin, None)
                self._owners.pop(abin, None)
                self._detached.discard(abin)
            self.worker.actors.pop(aid, None)
            if q is not None:
                q.put(_STOP)
        return None

    # ------------------------------------------------------- owner-death GC
    def _on_node_event(self, payload):
        """Kill hosted actors whose owning driver died (the head's
        monitor publishes every dead client here), unless detached."""
        if not isinstance(payload, dict) \
                or payload.get("event") != "node_dead":
            return
        dead_client = payload.get("client_id")
        with self._lock:
            doomed = [abin for abin, owner in self._owners.items()
                      if owner == dead_client
                      and abin not in self._detached]
        for abin in doomed:
            try:
                self._kill({"actor_id": abin, "no_restart": True})
            except Exception:  # noqa: BLE001 — already gone
                pass

    def shutdown(self):
        with self._lock:
            queues, self._queues = dict(self._queues), {}
        for q in queues.values():
            q.put(_STOP)
