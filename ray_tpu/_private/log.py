"""Minimal structured logging for the host control plane.

The daemon/server loops deliberately survive transient failures (head
restarts, dying peers, racing shutdowns) — but *silently* surviving
them is how a dead reporter thread goes unnoticed for a week. raylint's
exception-discipline pass forbids swallowing an exception in a loop
without logging it; this module is the sanctioned sink.

Usage::

    from ray_tpu._private.log import get_logger
    log = get_logger(__name__)
    ...
    except Exception as exc:  # transient: head not back yet
        log.debug("heartbeat failed; re-dialing: %r", exc)

Levels follow intent: ``debug`` for expected/transient conditions a
retry loop absorbs (off by default — zero noise in production),
``warning`` for conditions that should not happen but are survivable,
``error`` for giving up. The root ``ray_tpu`` logger gets one stderr
handler configured lazily; ``RAY_TPU_LOG_LEVEL`` (via
``_private/config.py``) sets the threshold, default ``warning``.
"""

from __future__ import annotations

import logging
import sys
import threading

_configure_lock = threading.Lock()
_configured = False


def _configure() -> None:
    global _configured
    with _configure_lock:
        if _configured:
            return
        _configured = True
        root = logging.getLogger("ray_tpu")
        if root.handlers:
            return  # the embedding app configured logging itself
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(
            "[ray_tpu %(levelname).1s %(name)s] %(message)s"))
        root.addHandler(handler)
        root.propagate = False
        try:
            from ray_tpu._private.config import GlobalConfig
            level = str(GlobalConfig.log_level).upper()
        except Exception as exc:  # config unimportable mid-bootstrap
            print(f"[ray_tpu] log config unavailable ({exc!r}); "
                  f"defaulting to WARNING", file=sys.stderr)
            level = "WARNING"
        root.setLevel(getattr(logging, level, logging.WARNING))


def get_logger(name: str) -> logging.Logger:
    """Logger under the ``ray_tpu`` hierarchy; accepts ``__name__`` or
    a bare suffix."""
    _configure()
    if not name.startswith("ray_tpu"):
        name = f"ray_tpu.{name}"
    return logging.getLogger(name)
