"""Driver-side router for pushing tasks onto remote node daemons.

Rebuild of the reference's cross-node scheduling path (reference roles:
owner-side lease requests submitted DIRECTLY to raylets + the object
directory/ObjectManager pull protocol [unverified]). A driver attached to
a head service sees the registered node daemons (``node_daemon.py``) and
routes tasks onto them when:

- the task's resource demand is **infeasible locally** (e.g. a custom
  resource only a remote node offers), or
- an explicit ``NodeAffinitySchedulingStrategy`` targets a daemon node, or
- the local backlog passes the spill threshold and a feasible node is
  less loaded (hybrid pack-then-spill, same policy family as
  ``cluster_utils.ClusterScheduler``).

The cross-node hot path keeps the head OUT of steady-state dispatch:

- **Direct dispatch** — the driver dials each node daemon's request
  server once (address published in the head's node directory, exactly
  like object servers) and pushes task payload batches peer-to-peer in
  one vectored ``send_many`` write per flush; a failed dial falls back
  to the head-relayed ``task_push``. Per-node single-flight draining
  means batches grow under load (flush-on-idle, the coalescer pattern).
- **Locality-aware placement** — ``_choose_node`` scores feasible nodes
  by ref-arg bytes already resident there (owners from the completion
  stream, sizes from ``task_done``; pending deps count as presence at
  their producer's node), so a task consuming a node-resident block
  runs *on that node* instead of forcing a chunked cross-node pull.
- **Per-node function cache** — ``cloudpickle.dumps(fn)`` ships once
  per (node, content digest); later payloads carry the digest only. A
  node that lost the digest (eviction, restart) answers ``need_fn`` and
  the payload reships with bytes.
- **Async dependency shipping** — tasks whose ref args are produced by
  OTHER router-tracked tasks ship immediately with pending pull-refs;
  the node daemon's prefetch machinery waits out the producer, so
  cross-node pipelines overlap instead of serializing on the driver.
  Producer failures propagate driver-side through recorded dep edges.

Data stays off the driver where possible: ref args whose values live on
a node travel as *pull refs* — the executing node pulls the serialized
bytes peer-to-peer (head-relayed chunks as fallback) from the owning
node, so a chain of remote tasks never round-trips the driver. Results
stay on the producing node until a consumer actually pulls them; task
ERRORS ride the ``task_done`` payload itself (no pullable bytes exist
for them) and materialize into the driver store on arrival.

Failure story: the router keeps the TaskSpec lineage of everything it
pushed. A node SIGKILL surfaces as a dead membership entry; in-flight
tasks re-route to surviving feasible nodes, and lost not-yet-pulled
result objects are re-executed from lineage on demand (ObjectRecovery
parity across real OS-process nodes).
"""

from __future__ import annotations

import pickle
import threading
import time
import weakref
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Set, Tuple

from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.log import get_logger
from ray_tpu._private.object_server import PeerUnreachableError
from ray_tpu._private.scheduler import TaskSpec, _collect_refs
from ray_tpu._private import tracing

log = get_logger(__name__)
from ray_tpu.exceptions import (
    GetTimeoutError,
    NodeDrainingError,
    RayTaskError,
    WorkerCrashedError,
)
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

_NODES_TTL_S = 0.5
_MAX_PUSH_ATTEMPTS = 3
_DRAINING_TTL_S = 60.0  # push-refusal cordon memory (reap follows soon)


class _DepNotReady(Exception):
    """A payload build found a dependency that must be awaited (owner
    died between dep classification and wiring). Raised instead of
    blocking: the spec re-enters _accept, whose blocker path waits on
    the dedicated blocking-wait pool — never on a drain lane."""


class RemoteRouter:
    def __init__(self, worker):
        self.worker = worker
        self.head = worker.head_client
        self.head.handlers["task_done"] = self._on_task_done_relayed
        # Completion fast path: nodes push task_done straight to this
        # driver's object/request server (address shipped in the task
        # payload) — the head only sees coalesced object announces.
        self.head._object_server.handlers["task_done"] = \
            self._on_task_done_direct
        # Streaming generators: per-yield item_done reports arrive on the
        # same direct plane (small items inline, large items announce +
        # p2p pull), exactly like task_done; the pub/sub topic
        # ``stream|<client>`` is the head-relayed fallback.
        self.head._object_server.handlers["item_done"] = self._on_item_done
        # Drain-before-reap receiving side: a draining node lease-
        # transfers the result bytes it holds for THIS owner in
        # object_offload flights — the bytes land in the local store
        # and the owner table re-points at ourselves, so borrowers keep
        # resolving after the node exits.
        self.head._object_server.handlers["object_offload"] = \
            self._on_object_offload
        # Node task-event shipping (observability): events ride the
        # task_done payloads; TAIL events (terminal records that raced
        # past the last completion flush) arrive on this side channel.
        self.head._object_server.handlers["task_events"] = \
            self._on_task_events
        self.lineage: Dict[TaskID, TaskSpec] = {}
        self._done: Dict[TaskID, threading.Event] = {}
        self._done_cbs: Dict[TaskID, List[Callable[[], None]]] = {}
        self._task_node: Dict[TaskID, str] = {}   # -> node client_id
        self._inflight: Dict[str, int] = {}       # node client -> pushed
        # Assigned-but-not-yet-delivered per node: counted into _load so
        # a burst CHOOSING nodes faster than batches hit the wire still
        # spreads (the in-flight counter alone lags by one drain cycle).
        self._assigned: Dict[str, int] = {}
        self._oid_owner: Dict[bytes, str] = {}    # done oids -> node client
        self._oid_sizes: Dict[bytes, int] = {}    # done oids -> byte size
        self._failed: Dict[TaskID, BaseException] = {}
        # Completed tids, marked INSIDE _on_task_done's locked block (the
        # done Events are set after the lock releases, too late for the
        # push-reply race check in _register_pushed). Recency-bounded:
        # the race window it closes is the push round trip, so old
        # entries are dead weight in a long-lived driver.
        self._completed: Set[TaskID] = set()
        self._completed_order: "deque" = deque()
        # Async dependency shipping: producer tid -> tids of pushed tasks
        # carrying a PENDING pull-ref on one of its outputs. A producer
        # failure fails the children promptly driver-side (the node-side
        # pull would otherwise only time out at the dep-wait bound).
        self._dep_children: Dict[TaskID, Set[TaskID]] = {}
        # Per-node function cache bookkeeping (driver side): digests this
        # driver has shipped to each node. Marked optimistically at
        # payload build; the node's ``need_fn`` reply self-heals a mark
        # that outran a failed push or a node-side eviction.
        self._fn_shipped: Dict[str, Set[bytes]] = {}
        self._fn_wire_cache: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()  # fn -> (digest, bytes)
        # Remote ACTOR tasks: completion tracked here (task_done +
        # object pull), but never re-executed from lineage — interrupted
        # actor calls fail (reference restart semantics); the
        # RemoteActorRuntime's watcher materializes the errors.
        self.external: Dict[TaskID, str] = {}     # tid -> node client_id
        self.remote_actors: List = []             # RemoteActorRuntime watch
        self._spread_counter = 0
        self._placed_counts: Dict[str, int] = {}  # node -> actors placed
        # Demand surface for the autoscaler: tasks no current node (and
        # no local capacity) can run are PARKED here until membership
        # changes; their shapes ride the driver's heartbeat status so
        # the autoscaler can provision nodes that fit (reference:
        # resource_demand in the raylet's load report).
        self._parked: List[TaskSpec] = []
        self._unmet_hints: List[tuple] = []  # (shape, ts) — actor asks
        if self.head.status_fn is None:
            self.head.status_fn = self._status
        self._recovering: set = set()
        self._prefetching: set = set()
        # Nodes that refused a push with "draining" (reap cordon):
        # skipped by _choose_node until the TTL lapses — the membership
        # heartbeat's draining marker takes over once it propagates.
        self._draining_nodes: Dict[str, float] = {}  # cid -> marked at
        self.drain_reroutes = 0    # pushes refused by a draining node
        self.offloaded_objects = 0  # drain lease-transfers received
        # Function-cache pre-ship: the last few distinct functions this
        # driver shipped anywhere (digest -> bytes, tiny LRU). A newly
        # joined node gets them pushed ahead of its first task, so the
        # cold-start fan-out wave skips the need_fn round trip.
        from collections import OrderedDict as _OrderedDict

        self._fn_recent: "_OrderedDict[bytes, bytes]" = _OrderedDict()
        self.fn_preship_sent = 0
        # Streaming generator bookkeeping: tasks whose consumption acks
        # this driver must propagate (consume-listener installed once per
        # task), the coalesced ack watermarks awaiting a wire flush, and
        # the per-task single-flight sender guard.
        self._stream_tasks: Set[TaskID] = set()
        self._stream_ack_pending: Dict[TaskID, int] = {}
        self._stream_ack_inflight: Set[TaskID] = set()
        self._stream_sub = False  # lazy fallback-topic subscription
        self._lock = threading.Lock()
        self._nodes_cache: tuple = (0.0, [])
        # Dispatch plane: a single grouping thread drains submitted
        # tasks into per-node pending lists; one in-flight push batch
        # per node (single-flight) means the NEXT batch accumulates
        # while the previous round trip is on the wire.
        self._dispatch_q: "deque" = deque()  # (spec, node|None, tried)
        self._dispatch_cv = threading.Condition()
        self._node_pending: Dict[str, list] = {}  # cid -> [(spec, tried)]
        self._node_busy: Set[str] = set()
        self._node_rec: Dict[str, dict] = {}      # cid -> membership rec
        # Prospective placement (assigned, possibly not yet pushed):
        # locality scoring colocates a fast chain's links through this
        # map before _task_node registration lands.
        self._task_target: Dict[TaskID, str] = {}
        # Ownership-based object directory (owner side): this driver
        # owns every ref its tasks return — the completion stream above
        # IS the location table, and peers resolve/subscribe against it
        # over the p2p object plane (``owner_locate``/``owner_notify``)
        # instead of asking the head. The head keeps only membership +
        # the fallback directory (lease handoff on shutdown).
        from ray_tpu._private.ownership import OwnerDirectory

        self.owner_directory = OwnerDirectory(self)
        # Bench counters (the cross-node fast-path proof surface).
        self.direct_pushes = 0     # tasks pushed peer-to-peer
        self.relayed_pushes = 0    # tasks pushed via head relay
        self.direct_batches = 0    # wire round trips on the direct plane
        self.direct_done_reports = 0   # completions pushed peer-to-peer
        self.relayed_done_reports = 0  # completions via head relay
        self.inline_results = 0    # results that arrived in task_done
        self.owner_table_pulls = 0  # result pulls resolved from the
        #                             owner's own table (no head RPC)
        self.fn_bytes_sent = 0     # function bytes actually shipped
        self.fn_payloads_with_bytes = 0
        self.fn_payloads_digest_only = 0
        self._pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="ray_tpu_router")
        # Blocking waits (prefetch ensure_local, dep awaits) get their
        # OWN pool so queued push batches and lineage re-execution on
        # self._pool never starve behind them.
        self._prefetch_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="ray_tpu_router_prefetch")
        self._stop = threading.Event()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="ray_tpu_router_dispatch")
        self._dispatcher.start()
        self._watcher = threading.Thread(
            target=self._watch_loop, daemon=True, name="ray_tpu_router_watch")
        self._watcher.start()
        try:
            # Membership events drive the function-cache pre-ship (a
            # joining node gets this driver's hot functions before its
            # first task) — best-effort; need_fn stays the safety net.
            self.head.subscribe("ray_tpu:node_events",
                                self._on_node_event)
        except Exception:  # noqa: BLE001 — headless/standalone runtime
            pass

    # ------------------------------------------------------------- routing
    def nodes(self, refresh: bool = False) -> List[dict]:
        now = time.monotonic()
        ts, cached = self._nodes_cache
        if not refresh and now - ts < _NODES_TTL_S:
            return cached
        try:
            nodes = self.head.node_list()
        except Exception:  # noqa: BLE001 — head unreachable: no routing
            nodes = []
        self._nodes_cache = (now, nodes)
        return nodes

    @staticmethod
    def _fits(node: dict, demand: Dict[str, float]) -> bool:
        res = node.get("resources") or {}
        return all(res.get(k, 0.0) >= v for k, v in demand.items())

    @staticmethod
    def _node_addr(node: dict) -> Optional[Tuple[str, int]]:
        """The node daemon's direct request/object server address
        (published through the node directory / its heartbeat)."""
        addr = node.get("peer_addr") or \
            (node.get("status") or {}).get("_peer_addr")
        return (str(addr[0]), int(addr[1])) if addr else None

    def _locality_bytes(self, spec: TaskSpec) -> Dict[str, int]:
        """Bytes of ``spec``'s ref args resident per node client. Owners
        and sizes come from the task_done stream; a PENDING dep (producer
        still running) counts as presence at its producer's node —
        weighted at the locality threshold so chains colocate."""
        loc: Dict[str, int] = {}
        for ref in _collect_refs(spec.args, spec.kwargs):
            ob = ref.object_id.binary()
            tid = ref.object_id.task_id()
            with self._lock:
                owner = self._oid_owner.get(ob)
                if owner is not None:
                    size = max(self._oid_sizes.get(ob, 0), 1)
                else:
                    owner = self._task_node.get(tid) or \
                        self._task_target.get(tid)
                    size = int(GlobalConfig.locality_min_bytes)
            if owner is not None:
                loc[owner] = loc.get(owner, 0) + size
        return loc

    def _is_draining(self, n: dict) -> bool:
        """Cordoned for reap: the heartbeat's draining marker, or a
        recent typed push refusal from the node itself (which beats the
        heartbeat by up to one period)."""
        if (n.get("status") or {}).get("draining"):
            return True
        if not self._draining_nodes:
            # Lock-free steady-state fast path: nothing has ever
            # drained, so don't pay lock contention per candidate per
            # task. The benign race (a refusal landing right now) is
            # already covered by the typed push refusal itself.
            return False
        with self._lock:
            ts = self._draining_nodes.get(n["client_id"])
            if ts is None:
                return False
            if time.monotonic() - ts > _DRAINING_TTL_S:
                self._draining_nodes.pop(n["client_id"], None)
                return False
        return True

    def _choose_node(self, spec: TaskSpec,
                     exclude: tuple = ()) -> Optional[dict]:
        nodes = [n for n in self.nodes()
                 if n.get("alive") and n["client_id"] not in exclude
                 and not self._is_draining(n)]
        strat = spec.scheduling_strategy
        if isinstance(strat, NodeAffinitySchedulingStrategy):
            for n in nodes:
                if n.get("node_id") == strat.node_id:
                    return n
            if not getattr(strat, "soft", False):
                return None
            # Soft affinity: target gone, fall through to least-loaded.
        feasible = [n for n in nodes if self._fits(n, spec.resources)]
        if not feasible:
            return None
        if len(feasible) > 1:
            # Locality-aware placement: the node already holding the
            # task's argument bytes wins over pure least-loaded, as long
            # as it is not drastically more loaded (slack bound) — the
            # reference's bytes-resident lease policy.
            loc = self._locality_bytes(spec)
            if loc:
                best = max(feasible,
                           key=lambda n: loc.get(n["client_id"], 0))
                resident = loc.get(best["client_id"], 0)
                if resident >= GlobalConfig.locality_min_bytes:
                    # Slack compares REPORTED backlogs (actually-runnable
                    # work), not the driver-side assignment counters: an
                    # async-shipped chain assigns all its links up front
                    # while only one is ever runnable — counting them as
                    # load would evict the chain from its data.
                    min_load = min(self._reported_load(n)
                                   for n in feasible)
                    if self._reported_load(best) <= \
                            min_load + GlobalConfig.locality_load_slack:
                        return best
        return min(feasible, key=self._load)

    @staticmethod
    def _reported_load(n: dict) -> float:
        """Heartbeat-reported backlog per CPU only — the node's actually
        runnable work, without this driver's assignment counters."""
        status = n.get("status") or {}
        cpus = max((n.get("resources") or {}).get("CPU", 1.0), 1.0)
        return float(status.get("backlog", 0)) / cpus

    def _load(self, n: dict) -> float:
        """Reported backlog (heartbeat, ~0.5 s stale) plus locally-known
        in-flight pushes, so a burst submitted between heartbeats spreads
        instead of piling onto one node."""
        status = n.get("status") or {}
        cpus = max((n.get("resources") or {}).get("CPU", 1.0), 1.0)
        with self._lock:
            inflight = self._inflight.get(n["client_id"], 0) \
                + self._assigned.get(n["client_id"], 0)
        return (float(status.get("backlog", 0)) + inflight) / cpus

    # ------------------------------------------------------ actor placement
    @staticmethod
    def actor_demand(opts: dict) -> Dict[str, float]:
        """Resource demand of an actor from its options (num_cpus +
        custom resources + PG bundle shape)."""
        demand: Dict[str, float] = {}
        if opts.get("num_cpus"):
            demand["CPU"] = float(opts["num_cpus"])
        strat = opts.get("scheduling_strategy")
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
        )

        if isinstance(strat, PlacementGroupSchedulingStrategy):
            # PG-aware placement: the bundle's resource shape is the
            # demand; the PG itself reserves per-node capacity only in
            # the sim plane, so here bundles steer feasibility.
            pg = strat.placement_group
            idx = strat.placement_group_bundle_index
            bundles = getattr(pg, "bundles", None) or []
            if bundles:
                bundle = bundles[max(idx, 0) % len(bundles)]
                demand.update({k: float(v) for k, v in bundle.items()})
        demand.update({k: float(v)
                       for k, v in (opts.get("resources") or {}).items()})
        return demand

    def place_actor(self, opts: dict) -> Optional[dict]:
        """Placement decision for a new actor (GcsActorScheduler role).
        Returns the hosting node's membership record, or None for a
        driver-local actor. Same policy family as maybe_route:

        - ``NodeAffinitySchedulingStrategy`` pins to that node;
        - a resource demand infeasible locally goes to a feasible node
          (loud error when none exists);
        - ``scheduling_strategy="SPREAD"`` round-robins over the local
          runtime + all feasible nodes;
        - thin clients (``ray://``) always place on the cluster;
        - otherwise the actor stays local (driver-owned, zero latency).
        """
        demand = self.actor_demand(opts)
        strat = opts.get("scheduling_strategy")
        # Draining nodes are cordoned for ACTORS too: placing onto a
        # node mid-reap creates the actor into a terminating process
        # (its creation either fails typed or strands node-side work).
        nodes = [n for n in self.nodes(refresh=True)
                 if n.get("alive") and not self._is_draining(n)]
        client_mode = getattr(self.worker, "client_mode", False)
        if isinstance(strat, NodeAffinitySchedulingStrategy):
            if strat.node_id == self.worker.node_id.hex() \
                    and not client_mode:
                return None
            for n in nodes:
                if n.get("node_id") == strat.node_id:
                    return n
            if not getattr(strat, "soft", False):
                raise ValueError(
                    f"no alive node {strat.node_id!r} for actor "
                    f"NodeAffinity placement")
        feasible = [n for n in nodes if self._fits(n, demand)]
        local_fits = (self.worker.resource_pool.fits(demand)
                      and not client_mode)
        if not local_fits:
            if not feasible:
                # Record the shape so an autoscaler can provision for a
                # retry, then fail loudly (actor creation is synchronous
                # — it cannot park like a task).
                with self._lock:
                    self._unmet_hints.append((dict(demand),
                                              time.monotonic()))
                # Cold-start chain: the request that exposed the
                # capacity gap parks its trace context so the
                # autoscaler's launch (and the launched node's init /
                # head join) lands in the same trace.
                tracing.stash_cold_start()
                from ray_tpu.exceptions import PlacementInfeasibleError

                raise PlacementInfeasibleError(
                    f"actor resource demand {demand} is infeasible: no "
                    f"local capacity and no feasible cluster node")
            return self._record_placement(
                min(feasible, key=self._actor_load))
        if strat == "SPREAD" and feasible:
            # Round-robin across local + feasible nodes so replica/worker
            # groups land on every machine.
            with self._lock:
                slot = self._spread_counter
                self._spread_counter += 1
            candidates: List[Optional[dict]] = [None] + feasible
            return self._record_placement(
                candidates[slot % len(candidates)])
        return None

    def _record_placement(self, node: Optional[dict]) -> Optional[dict]:
        """Count placements locally so a burst placed between heartbeats
        spreads instead of piling onto one node (same trick as the task
        router's in-flight counter)."""
        if node is not None:
            with self._lock:
                cid = node["client_id"]
                self._placed_counts[cid] = \
                    self._placed_counts.get(cid, 0) + 1
        return node

    def _actor_load(self, n: dict) -> float:
        status = n.get("status") or {}
        with self._lock:
            placed = self._placed_counts.get(n["client_id"], 0)
        # The heartbeat-reported count eventually includes our local
        # placements; take the max so they are not double-counted.
        return max(float(status.get("actors", 0)), float(placed)) \
            + self._load(n)

    def register_external(self, tid: TaskID, node_client: str):
        """Track a remote actor task: completion arrives via task_done;
        the result oids resolve through ensure_local like routed tasks."""
        with self._lock:
            self.external[tid] = node_client
            self._done.setdefault(tid, threading.Event())

    def watch_remote_actor(self, runtime):
        """Register a RemoteActorRuntime for node-death watching (fail
        in-flight calls + restart-on-surviving-node)."""
        with self._lock:
            self.remote_actors.append(runtime)

    # --------------------------------------------------------- demand report
    def unmet_shapes(self) -> List[Dict[str, float]]:
        """Resource shapes this driver wants but no current node serves
        (parked tasks + recent infeasible actor asks) — the autoscaler's
        scale-up signal."""
        now = time.monotonic()
        with self._lock:
            self._unmet_hints = [(s, ts) for s, ts in self._unmet_hints
                                 if now - ts < 30.0]
            return [dict(s.resources) for s in self._parked] + \
                [dict(s) for s, _ in self._unmet_hints]

    def _status(self) -> dict:
        return {
            "backlog": self.worker.scheduler.backlog_size(),
            "unmet": self.unmet_shapes(),
        }

    def _retry_parked(self):
        with self._lock:
            parked, self._parked = self._parked, []
        still = []
        for spec in parked:
            node = self._choose_node(spec)
            if node is None:
                still.append(spec)
            else:
                self._accept(spec, node)
        if still:
            with self._lock:
                self._parked = still + self._parked

    def maybe_route(self, spec: TaskSpec) -> bool:
        """Called by Worker.submit_task before local submission. Returns
        True iff the task was taken over for remote execution."""
        strat = spec.scheduling_strategy
        affinity_remote = (
            isinstance(strat, NodeAffinitySchedulingStrategy)
            and any(n.get("node_id") == strat.node_id
                    for n in self.nodes()))
        local_fits = (self.worker.resource_pool.fits(spec.resources)
                      and not getattr(self.worker, "client_mode", False))
        spill = False
        if local_fits and not affinity_remote:
            backlog = self.worker.scheduler.backlog_size()
            cpus = max(
                self.worker.resource_pool.total.get("CPU", 1.0), 1.0)
            spill = backlog / cpus > GlobalConfig.spill_backlog_factor
        if not (affinity_remote or not local_fits or spill):
            return False
        node = self._choose_node(spec)
        if node is None:
            hard_affinity = (isinstance(strat, NodeAffinitySchedulingStrategy)
                            and not getattr(strat, "soft", False))
            if not local_fits and not hard_affinity \
                    and not getattr(self.worker, "client_mode", False):
                # Infeasible EVERYWHERE: park it and advertise the shape
                # so an autoscaler can provision a node that fits; the
                # watch loop retries when membership changes. (Thin
                # clients keep their loud no-capacity error; a hard
                # NodeAffinity miss is a strategy miss, not a resource
                # shape an autoscaler could satisfy — don't park it.)
                with self._lock:
                    self._parked.append(spec)
                    self.lineage[spec.task_id] = spec
                    self._done.setdefault(spec.task_id, threading.Event())
                return True
            return False
        if not local_fits or affinity_remote or self._node_less_loaded(node):
            self._accept(spec, node)
            return True
        return False

    def _node_less_loaded(self, node: dict) -> bool:
        status = node.get("status") or {}
        cpus = max((node.get("resources") or {}).get("CPU", 1.0), 1.0)
        local_cpus = max(
            self.worker.resource_pool.total.get("CPU", 1.0), 1.0)
        return (float(status.get("backlog", 0)) / cpus
                < self.worker.scheduler.backlog_size() / local_cpus)

    # ---------------------------------------------------------- acceptance
    def _accept(self, spec: TaskSpec, node: Optional[dict],
                tried: tuple = ()):
        """Take ownership of a spec for remote execution. Deps produced
        by other ROUTER-TRACKED tasks do NOT block shipping (they travel
        as pending pull-refs — async dependency shipping); only deps the
        driver itself must inline (untracked local producers) hold the
        task back, on the blocking-wait pool, event-driven."""
        if spec.streaming:
            self._track_stream(spec)
        with self._lock:
            self.lineage[spec.task_id] = spec
            self._done.setdefault(spec.task_id, threading.Event())
            if node is not None:
                cid = node["client_id"]
                self._assigned[cid] = self._assigned.get(cid, 0) + 1
                # Prospective target recorded at CHOICE time, not at
                # dispatch: the next link of a fast-submitted chain
                # must see its parent's placement to colocate.
                self._task_target[spec.task_id] = cid
        blockers = self._dep_blockers(spec)
        if blockers:
            self._prefetch_pool.submit(
                self._await_then_enqueue, spec, node, tried, blockers)
        else:
            self._enqueue(spec, node, tried)

    def _dep_blockers(self, spec: TaskSpec) -> List[ObjectID]:
        """Ref args that must be resolved driver-side before the task
        can ship: not store-ready, not served by a live owner, and not
        produced by a STILL-RUNNING tracked task (those ship as pending
        pull-refs instead). A tracked dep that COMPLETED but lost its
        owner (node died after finishing) blocks too — it needs
        lineage recovery, not a doomed directory poll."""
        blockers: List[ObjectID] = []
        for ref in _collect_refs(spec.args, spec.kwargs):
            oid = ref.object_id
            if self.worker.store.is_ready(oid):
                continue
            ob = oid.binary()
            tid = oid.task_id()
            with self._lock:
                owner = self._oid_owner.get(ob)
                ev = self._done.get(tid)
                done = ev is not None and ev.is_set()
                tracked = (tid in self.lineage or tid in self.external) \
                    and tid not in self._failed
            if owner is not None and self._client_alive(owner):
                continue
            if tracked and not done:
                continue  # pending: ships as an async pull-ref
            blockers.append(oid)
        return blockers

    def _await_blocker(self, oid: ObjectID):
        """Resolve one blocking dep on the wait pool: a tracked dep
        that completed but lost its owner goes through ensure_local
        (pull-or-re-execute-from-lineage — the recovery semantics);
        anything else waits event-driven for production."""
        tid = oid.task_id()
        with self._lock:
            ev = self._done.get(tid)
            done = ev is not None and ev.is_set()
            tracked = (tid in self.lineage or tid in self.external) \
                and tid not in self._failed
        if tracked and done and not self.worker.store.is_ready(oid):
            self.ensure_local(oid, timeout=GlobalConfig.dep_wait_s)
            return
        self._await_dep(oid)

    def _await_then_enqueue(self, spec: TaskSpec, node: Optional[dict],
                            tried: tuple, blockers: List[ObjectID]):
        try:
            for oid in blockers:
                self._await_blocker(oid)
        except BaseException as exc:  # noqa: BLE001 — dep failed/timed out
            if node is not None:
                with self._lock:
                    self._dec_assigned_locked(node["client_id"])
            self._fail(spec, exc)
            return
        self._enqueue(spec, node, tried)

    def _enqueue(self, spec: TaskSpec, node: Optional[dict],
                 tried: tuple = ()):
        with self._dispatch_cv:
            if self._stop.is_set():
                return
            self._dispatch_q.append((spec, node, tuple(tried)))
            self._dispatch_cv.notify()

    # ------------------------------------------------------------ dispatch
    def _dispatch_loop(self):
        """Group submitted tasks by target node and drain them through
        per-node single-flight batches: while one batch's round trip is
        in flight, the node's next batch accumulates — so a fan-out
        burst rides a handful of vectored writes, not N round trips."""
        while True:
            with self._dispatch_cv:
                while not self._dispatch_q and not self._stop.is_set():
                    self._dispatch_cv.wait()
                if self._stop.is_set():
                    return
                items = list(self._dispatch_q)
                self._dispatch_q.clear()
            to_start = []
            for spec, node, tried in items:
                assigned_here = node is None
                if node is None:
                    node = self._choose_node(spec, exclude=tried)
                if node is None:
                    self._fail(spec, WorkerCrashedError(
                        f"no reachable node accepted task {spec.name!r}"))
                    continue
                cid = node["client_id"]
                with self._lock:
                    self._node_rec[cid] = node
                    self._task_target[spec.task_id] = cid
                    if assigned_here:
                        self._assigned[cid] = \
                            self._assigned.get(cid, 0) + 1
                    self._node_pending.setdefault(cid, []).append(
                        (spec, tried))
                    if cid not in self._node_busy:
                        self._node_busy.add(cid)
                        to_start.append(cid)
            for cid in to_start:
                self._pool.submit(self._drain_node, cid)

    def _drain_node(self, cid: str):
        while True:
            with self._lock:
                entries = self._node_pending.pop(cid, [])
                if not entries:
                    self._node_busy.discard(cid)
                    return
                node = self._node_rec.get(cid)
            try:
                self._push_group(node, entries)
            except Exception as exc:  # noqa: BLE001 — batch boundary
                for spec, _ in entries:
                    self._fail(spec, exc)

    def _push_group(self, node: dict, entries: list):
        cid = node["client_id"]
        addr = self._node_addr(node)
        built = []
        for spec, tried in entries:
            try:
                built.append((spec, tried,
                              self._build_payload(spec, cid)))
            except _DepNotReady:
                # A dep must be awaited after all: re-accept (node
                # re-chosen after the wait — the owner it was placed
                # for may be gone).
                with self._lock:
                    self._dec_assigned_locked(cid)
                self._accept(spec, None, tried)
            except BaseException as exc:  # noqa: BLE001 — per-spec build
                with self._lock:
                    self._dec_assigned_locked(cid)
                self._fail(spec, exc)
        if built:
            self._deliver(cid, addr, built, reship_ok=True)

    def _deliver(self, cid: str, addr, built: list, reship_ok: bool,
                 transfer: bool = True):
        """Push one batch of built payloads to a node: direct plane
        first, head relay as the fallback. In-flight accounting is
        ATOMIC with push success: a task registers in ``_task_node``
        only once its payload was accepted (or decrements right away if
        its completion raced the reply), so the watch loop can never
        observe a half-pushed registration and double-re-execute."""
        payloads = [p for _, _, p in built]
        with self._lock:
            if transfer:  # assignment graduates to in-flight at wire time
                for _ in built:
                    self._dec_assigned_locked(cid)
            self._inflight[cid] = self._inflight.get(cid, 0) + len(built)
        try:
            replies = self._send_batch(cid, addr, payloads)
        except Exception as exc:  # noqa: BLE001 — node unreachable
            with self._lock:
                for _ in built:
                    self._dec_inflight_locked(cid)
            for spec, tried, _ in built:
                self._retry_or_fail(spec, tried + (cid,), exc)
            return
        reship = []
        for (spec, tried, _), rep in zip(built, replies):
            if rep == "accepted":
                self._register_pushed(spec.task_id, cid)
                if spec.streaming:
                    # Replayed producers start a FRESH StreamState with
                    # consumed=0 on the new node; without re-sending the
                    # consumer's watermark the replay parks at the
                    # backpressure budget before re-reaching the
                    # consumer's index and the stream deadlocks — acks
                    # otherwise fire only on NEW consumption.
                    st = self.worker.streams.get(spec.task_id)
                    if st is not None and st.consumed > 0:
                        self._send_stream_ack(spec.task_id, st.consumed)
            elif rep == "draining":
                # Reap race: the node was chosen for reap while this
                # push was in flight. Typed refuse-and-reroute — cordon
                # the node locally and re-dispatch elsewhere (counted;
                # never a task failure).
                with self._lock:
                    self._dec_inflight_locked(cid)
                    self._draining_nodes[cid] = time.monotonic()
                    self.drain_reroutes += 1
                self._retry_or_fail(spec, tried + (cid,),
                                    NodeDrainingError(cid))
            elif rep == "need_fn" and reship_ok:
                # The node lost (or never saw) this digest: rebuild with
                # the function bytes forced in and push once more.
                with self._lock:
                    self._dec_inflight_locked(cid)
                try:
                    reship.append((spec, tried, self._build_payload(
                        spec, cid, force_fn=True)))
                except _DepNotReady:
                    # A dep's owner vanished mid-reship: back through
                    # the blocker path, same as the first-build case.
                    self._accept(spec, None, tried)
                except BaseException as exc:  # noqa: BLE001
                    self._fail(spec, exc)
            else:
                exc = rep if isinstance(rep, BaseException) else \
                    WorkerCrashedError(
                        f"node {cid} rejected task {spec.name!r}: {rep!r}")
                with self._lock:
                    self._dec_inflight_locked(cid)
                self._retry_or_fail(spec, tried + (cid,), exc)
        if reship:
            self._deliver(cid, addr, reship, reship_ok=False,
                          transfer=False)

    def _send_batch(self, cid: str, addr, payloads: list) -> list:
        """One wire round trip carrying the whole batch. Direct plane
        (vectored send_many to the node's server) unless disabled or
        unreachable; head-relayed task_push batch otherwise (those ride
        the head client's request coalescer — still ~1 round trip)."""
        if GlobalConfig.direct_dispatch and addr is not None:
            try:
                replies = self.head.task_push_direct(addr, payloads)
                with self._lock:
                    self.direct_pushes += len(payloads)
                    self.direct_batches += 1
                return replies
            except PeerUnreachableError:
                pass  # NAT / dead dial: control-plane fallback below
        replies = self.head.task_push_many(cid, payloads)
        with self._lock:
            self.relayed_pushes += len(payloads)
        return replies

    def _register_pushed(self, tid: TaskID, cid: str):
        with self._lock:
            if tid in self._completed or tid in self._failed:
                # task_done (or a failure) raced the push reply: the
                # completion path never saw a _task_node entry, so the
                # in-flight count is settled here instead. (_completed
                # is written inside _on_task_done's locked block — the
                # done Event is set too late to close this race.)
                self._dec_inflight_locked(cid)
            else:
                self._task_node[tid] = cid

    def _retry_or_fail(self, spec: TaskSpec, tried: tuple,
                       exc: BaseException):
        if len(tried) >= _MAX_PUSH_ATTEMPTS:
            self._fail(spec, WorkerCrashedError(
                f"no reachable node accepted task {spec.name!r} "
                f"(last error: {exc})"))
        else:
            self._enqueue(spec, None, tried)

    # ---------------------------------------------------------------- wire
    def _fn_wire(self, fn) -> Tuple[bytes, bytes]:
        """(digest, cloudpickle bytes) of a task function, serialized
        ONCE per function object per driver (weak-keyed cache)."""
        try:
            cached = self._fn_wire_cache.get(fn)
        except TypeError:
            cached = None
        if cached is not None:
            return cached
        import hashlib

        import cloudpickle

        fnb = cloudpickle.dumps(fn)
        cached = (hashlib.sha256(fnb).digest(), fnb)
        try:
            self._fn_wire_cache[fn] = cached
        except TypeError:  # unhashable/unweakrefable callable
            pass
        with self._lock:
            # Hot-function LRU feeding the node-join pre-ship (small,
            # bytes-bounded by entry count — fat closures are capped by
            # the node-side cache anyway).
            self._fn_recent[cached[0]] = fnb
            self._fn_recent.move_to_end(cached[0])
            while len(self._fn_recent) > 8:
                self._fn_recent.popitem(last=False)
        return cached

    def _build_payload(self, spec: TaskSpec, cid: str,
                       force_fn: bool = False) -> bytes:
        ctx = self.worker.serialization_context
        pending_refs: List[bytes] = []  # producers still in flight

        def _wire_arg(v):
            from ray_tpu._private.worker import ObjectRef

            if not isinstance(v, ObjectRef):
                return ("v", ctx.serialize(v).to_bytes())
            oid = v.object_id
            ob = oid.binary()
            tid = oid.task_id()
            with self._lock:
                owner = self._oid_owner.get(ob)
            if owner is not None and self._client_alive(owner):
                return ("r", ob)
            if self.worker.store.is_ready(oid):
                # Driver-local (or recovered-to-driver) value: inline it.
                value = self.worker.get_object(v)
                return ("v", ctx.serialize(value).to_bytes())
            with self._lock:
                # Failure re-check and dep-edge registration are ONE
                # critical section with _fail's pop of _dep_children:
                # either we see the producer's failure here, or _fail
                # sees (and fires) the edge we registered — a child can
                # never ship against a failed producer unnotified.
                exc = self._failed.get(tid)
                if exc is not None:
                    raise exc
                ev = self._done.get(tid)
                done = ev is not None and ev.is_set()
                tracked = tid in self.lineage or tid in self.external
                if tracked and not done:
                    # Pending pull-ref (async dependency shipping): ship
                    # NOW and let the node daemon wait out the producer.
                    self._dep_children.setdefault(tid, set()).add(
                        spec.task_id)
                    pending_refs.append(ob)
            if tracked and not done:
                return ("r", ob)
            # Completed-but-ownerless (node died holding the bytes) or
            # untracked producer that slipped past the blocker check:
            # do NOT block this drain lane — bounce the spec back
            # through _accept, whose blocker path recovers (lineage
            # re-execution / event-driven wait) on the dedicated pool.
            raise _DepNotReady()

        digest, fnb = self._fn_wire(spec.function)
        with self._lock:
            shipped = self._fn_shipped.setdefault(cid, set())
            include_fn = force_fn or digest not in shipped
            if include_fn:
                # Optimistic mark: a push that later fails leaves a stale
                # mark, which the node's need_fn reply self-heals.
                shipped.add(digest)
        import os as _os

        payload = {
            "driver_id": self.head.client_id,
            # The driver's own object/request server: nodes push
            # task_done straight back here (head out of the completion
            # path) when they can dial it.
            "driver_addr": list(self.head._object_server.address),
            # Unique per BUILD: the node dedupes (task_id, push_id), so
            # a verbatim resend after an ambiguous wire failure cannot
            # double-execute, while deliberate re-pushes (new build)
            # are admitted.
            "push_id": _os.urandom(8),
            "task_id": spec.task_id.binary(),
            "return_ids": [o.binary() for o in spec.return_ids],
            "num_returns": spec.num_returns,
            "name": spec.name,
            "resources": spec.resources,
            "max_retries": spec.max_retries,
            "retry_exceptions": spec.retry_exceptions,
            "runtime_env": spec.runtime_env,
            "fn_digest": digest,
            "args": [_wire_arg(a) for a in spec.args],
            "kwargs": {k: _wire_arg(v) for k, v in spec.kwargs.items()},
        }
        if spec.streaming:
            # Streaming generator: the node commits one object per yield
            # and pushes per-item ``item_done`` reports back over this
            # same direct plane; the backpressure budget governs its
            # yield loop, resumed by this driver's consumption acks.
            payload["streaming"] = True
            payload["backpressure"] = int(spec.backpressure)
        if spec.trace is not None and tracing._TRACER is not None:
            # Trace context rides the task dict (tracing off = key
            # absent = zero extra wire bytes); the node daemon's
            # task-event bridge emits accept/queue/exec spans under it.
            payload["trace"] = tuple(spec.trace)
        if pending_refs:
            # The node gates THESE refs on its wait plane; ordinary
            # owner-resolvable pull-refs stay on its bounded pull pools.
            payload["pending_refs"] = pending_refs
        with self._lock:
            if include_fn:
                payload["fn"] = fnb
                self.fn_bytes_sent += len(fnb)
                self.fn_payloads_with_bytes += 1
            else:
                self.fn_payloads_digest_only += 1
        return pickle.dumps(payload, protocol=5)

    # -------------------------------------------------------------- failure
    def _fail(self, spec: TaskSpec, exc: BaseException):
        """Fail a task and, iteratively, every async-shipped dependent
        recorded against it (a worklist, NOT recursion — a failed
        1000-link chain must not blow the stack mid-cascade and leave
        tail tasks waiting out the dep bound)."""
        if not isinstance(exc, (RayTaskError, WorkerCrashedError)):
            exc = RayTaskError.from_exception(spec.name, exc)
        work: deque = deque([spec])
        while work:
            s = work.popleft()
            for oid in s.return_ids:
                self.worker.store.put_error(oid, exc)
            tid = s.task_id
            with self._lock:
                self._failed[tid] = exc
                self._task_target.pop(tid, None)
                children = self._dep_children.pop(tid, set())
                ev = self._done.get(tid)
            if ev is not None:
                ev.set()
            self._notify_done(tid)
            self.owner_directory.publish_many(
                [o.binary() for o in s.return_ids])
            # Dependents can never run now — fail them too instead of
            # letting their node-side pulls stall to the dep bound.
            for ctid in children:
                with self._lock:
                    cspec = None if ctid in self._failed \
                        else self.lineage.get(ctid)
                if cspec is not None:
                    work.append(cspec)

    def _fail_downstream(self, tid: TaskID, exc: BaseException):
        with self._lock:
            if tid in self._failed:
                return
            spec = self.lineage.get(tid)
        if spec is not None:
            self._fail(spec, exc)

    # ------------------------------------------------------- dep resolution
    def _on_done(self, tid: TaskID, cb: Callable[[], None]):
        """Run ``cb`` when the task's completion event fires (now, if it
        already has) — the event-driven edge `_await_dep` waits on."""
        with self._lock:
            ev = self._done.get(tid)
            if ev is None or not ev.is_set():
                self._done_cbs.setdefault(tid, []).append(cb)
                return
        cb()

    def _notify_done(self, tid: TaskID):
        with self._lock:
            cbs = self._done_cbs.pop(tid, [])
        for cb in cbs:
            try:
                cb()
            except Exception:  # noqa: BLE001 — waiter callback bug
                pass

    def _await_dep(self, object_id: ObjectID,
                   timeout: Optional[float] = None):
        """Event-driven wait until a dependency is PRODUCED — locally
        ready in the store, or completed by a router-tracked remote task
        (wherever its bytes live). No poll loops: the store's on_ready
        callback and the router's completion callbacks both flip one
        event. Raises the producer's error if it failed, or a typed
        ``GetTimeoutError`` after ``RAY_TPU_DEP_WAIT_S``."""
        if timeout is None:
            timeout = GlobalConfig.dep_wait_s
        tid = object_id.task_id()
        produced = threading.Event()
        self.worker.store.on_ready(object_id, produced.set)
        with self._lock:
            tracked = (tid in self._done or tid in self.lineage
                       or tid in self.external)
        if tracked:
            # Untracked producers never fire _notify_done — registering
            # would leak the callback forever; their completion signal
            # is the store's on_ready above.
            self._on_done(tid, produced.set)
        if not produced.wait(timeout):
            raise GetTimeoutError(
                f"dependency {object_id.hex()[:16]}… was not produced "
                f"within {timeout:.0f}s (RAY_TPU_DEP_WAIT_S)")
        with self._lock:
            exc = self._failed.get(tid)
        if exc is not None:
            raise exc
        err = self.worker.store.peek_error(object_id)
        if err is not None:
            raise err

    def _client_alive(self, client_id: str) -> bool:
        return any(n["client_id"] == client_id and n.get("alive")
                   for n in self.nodes())

    def _holder_addr(self, client_id: str) -> Optional[Tuple[str, int]]:
        """Direct object-server address of the node currently holding
        an object's bytes (owner directory answers carry this)."""
        with self._lock:
            node = self._node_rec.get(client_id)
        if node is None:
            node = next((n for n in self.nodes()
                         if n["client_id"] == client_id), None)
        return self._node_addr(node) if node else None

    # ----------------------------------------------------------- completion
    def _dec_inflight_locked(self, cid: str):
        n = self._inflight.get(cid, 0) - 1
        if n <= 0:
            self._inflight.pop(cid, None)
        else:
            self._inflight[cid] = n

    def _dec_assigned_locked(self, cid: str):
        n = self._assigned.get(cid, 0) - 1
        if n <= 0:
            self._assigned.pop(cid, None)  # floor at zero: transient
        else:                              # imprecision must not stick
            self._assigned[cid] = n

    def _on_task_done_direct(self, msg: tuple):
        with self._lock:
            self.direct_done_reports += 1
        return self._on_task_done(msg)

    def _on_task_done_relayed(self, event: tuple):
        with self._lock:
            self.relayed_done_reports += 1
        return self._on_task_done(event)

    def _on_task_done(self, event: tuple):
        from ray_tpu._private.serialization import SerializedObject

        payload = pickle.loads(event[1])
        tid = TaskID(payload["task_id"])
        # Task errors ride the done payload (no pullable bytes exist for
        # them): materialize them locally so gets raise promptly instead
        # of pull-looping against an owner that can never serve them.
        err_objs: Dict[bytes, BaseException] = {}
        first_exc: Optional[BaseException] = None
        for ob, eb in (payload.get("errs") or {}).items():
            try:
                exc = pickle.loads(eb)
            except Exception:  # noqa: BLE001 — error didn't survive wire
                exc = WorkerCrashedError(
                    "remote task failed and its error was not "
                    "transferable")
            err_objs[bytes(ob)] = exc
            if first_exc is None:
                first_exc = exc
        with self._lock:
            for ob in payload["oid_bins"]:
                ob = bytes(ob)
                if ob in err_objs:
                    self._oid_owner.pop(ob, None)
                else:
                    self._oid_owner[ob] = payload["node_client"]
            for ob, sz in (payload.get("sizes") or {}).items():
                self._oid_sizes[bytes(ob)] = int(sz)
            while len(self._oid_sizes) > 131072:
                # Locality hints only — recency-bounded (FIFO via dict
                # insertion order), unlike the pre-existing lineage maps.
                self._oid_sizes.pop(next(iter(self._oid_sizes)))
            self._completed.add(tid)
            self._completed_order.append(tid)
            while len(self._completed_order) > 65536:
                self._completed.discard(self._completed_order.popleft())
            cid = self._task_node.pop(tid, None)
            if cid is not None:
                self._dec_inflight_locked(cid)
            self._task_target.pop(tid, None)
            # Stream bookkeeping ends with the task: no more item
            # reports will need acks, and leaving entries behind grows
            # the router unboundedly under continuous streaming load.
            self._stream_tasks.discard(tid)
            self._stream_ack_pending.pop(tid, None)
            if first_exc is not None:
                self._failed.setdefault(tid, first_exc)
            children = self._dep_children.pop(tid, set())
            ev = self._done.setdefault(tid, threading.Event())
        for ob, exc in err_objs.items():
            self.worker.store.put_error(ObjectID(ob), exc)
        # Small results ride the done payload itself (the reference's
        # small-return-to-owner path): materialize them before waking
        # waiters, so gets never pay a pull round trip for them.
        for ob, raw in (payload.get("inline") or {}).items():
            self.worker.store.put(
                ObjectID(bytes(ob)), SerializedObject.from_bytes(raw))
            with self._lock:
                self.inline_results += 1
        ev.set()
        self._notify_done(tid)
        # Owner directory: wake any peer subscribed to these results
        # (no-op when nobody asked — the common case).
        self.owner_directory.publish_many(
            [bytes(ob) for ob in payload["oid_bins"]])
        if first_exc is not None:
            for ctid in children:
                self._fail_downstream(ctid, first_exc)
        # Node task events ride home on this report (zero new head
        # RPCs): merge them so util.state.list_tasks() sees cluster
        # tasks, and stamp the driver-side completion into the trace.
        shipped = payload.get("node_events")
        if shipped:
            node_client = payload["node_client"]
            self.worker.task_events.ingest(
                (TaskID(bytes(tb)), state, ts, name, dur, node_client)
                for tb, state, ts, name, dur in shipped)
        if tracing._TRACER is not None:
            ctx = tracing.task_context(bytes(payload["task_id"]))
            if ctx is not None:
                tracing.event("task.done", ctx=ctx,
                              node=payload["node_client"],
                              error=str(first_exc is not None))
        return None

    def _on_task_events(self, msg: tuple):
        """Tail task events from a node (no completion report left to
        ride): merge them into the driver's state-API ring."""
        node_client, events = pickle.loads(bytes(msg[1]))
        return self.worker.task_events.ingest(
            (TaskID(bytes(tb)), state, ts, name, dur, node_client)
            for tb, state, ts, name, dur in events)

    # --------------------------------------------------------------- drain
    def _on_object_offload(self, msg: tuple):
        """A draining node lease-transfers result bytes it holds for
        this owner: store them locally and re-point the owner table at
        ourselves — borrowers' ``owner_locate`` then resolves against
        OUR store/server, and reap cannot strand the refs."""
        from ray_tpu._private.serialization import SerializedObject

        stored = 0
        for ob, raw in msg[1]:
            oid = ObjectID(bytes(ob))
            if not self.worker.store.is_ready(oid):
                self.worker.store.put(
                    oid, SerializedObject.from_bytes(bytes(raw)))
            with self._lock:
                # Local bytes win every later lookup (OwnerDirectory
                # checks the store first); drop the stale holder entry.
                self._oid_owner.pop(bytes(ob), None)
                self.offloaded_objects += 1
            stored += 1
        self.owner_directory.publish_many(
            [bytes(ob) for ob, _ in msg[1]])
        return stored

    def _on_node_event(self, payload):
        """Membership event (head pub/sub): a newly joined node gets
        this driver's hot function bytes pushed ahead of its first
        task (cold-start attack: the first fan-out wave on a fresh
        autoscaled node skips the need_fn round trip)."""
        try:
            if not isinstance(payload, dict) or \
                    payload.get("event") != "node_added":
                return
            cid = payload.get("client_id")
            with self._lock:
                fn_bytes = list(self._fn_recent.values())
            if not fn_bytes or cid is None:
                return
            self._prefetch_pool.submit(self._preship_fns, cid, fn_bytes)
        except Exception:  # noqa: BLE001 — keep the event thread alive
            pass

    def _preship_fns(self, cid: str, fn_bytes: list):
        # The join event can beat the node's first heartbeat (which
        # carries its direct-server address): wait it out briefly.
        addr = None
        for _ in range(20):
            node = next((n for n in self.nodes(refresh=True)
                         if n["client_id"] == cid), None)
            addr = self._node_addr(node) if node else None
            if addr is not None or self._stop.is_set():
                break
            time.sleep(0.25)
        if addr is None:
            return
        try:
            self.head._peers.call(addr, ("fn_preship", fn_bytes))
            import hashlib

            with self._lock:
                self.fn_preship_sent += len(fn_bytes)
                # Mark the digests shipped for this node: payload
                # builds go digest-only on the first push (the whole
                # point); the node's need_fn reply self-heals any
                # divergence, same as every other stale mark.
                shipped = self._fn_shipped.setdefault(cid, set())
                for fnb in fn_bytes:
                    shipped.add(hashlib.sha256(fnb).digest())
        except Exception as exc:  # noqa: BLE001 — cold node not yet
            log.debug("fn pre-ship to %s failed (need_fn covers it): "
                      "%r", cid, exc)

    # ----------------------------------------------------------- streaming
    def _track_stream(self, spec: TaskSpec):
        """First acceptance of a streaming spec: install the consumption
        listener (acks propagate to whichever node currently runs the
        producer) and the head-relayed fallback subscription."""
        with self._lock:
            if spec.task_id in self._stream_tasks:
                return  # re-accept (replay): listener already installed
            self._stream_tasks.add(spec.task_id)
            need_sub = not self._stream_sub
            self._stream_sub = True
        if need_sub:
            try:
                self.head.subscribe(f"stream|{self.head.client_id}",
                                    self._on_stream_pub)
            except Exception:  # noqa: BLE001 — direct plane still works
                pass
        stream = self.worker.streams.get_or_create(spec.task_id)
        stream.add_consume_listener(
            lambda n, _tid=spec.task_id: self._send_stream_ack(_tid, n))

    def _on_stream_pub(self, payload):
        """Head-relayed fallback for per-item reports (NAT'd nodes)."""
        try:
            if payload and payload[0] == "item_done":
                self._on_item_done(("item_done", payload[1]))
        except Exception:  # noqa: BLE001 — keep the event thread alive
            pass

    def _on_item_done(self, msg: tuple):
        """One yield committed on the producing node: small items arrive
        INLINE (materialize -> the consumer's next() unblocks on the
        store event); large items record owner + size so next() drives a
        p2p pull."""
        from ray_tpu._private.serialization import SerializedObject

        payload = pickle.loads(bytes(msg[1]))
        tid = TaskID(bytes(payload["task_id"]))
        stream = self.worker.streams.get(tid)
        if stream is None:
            # The consumer already closed/released this stream: a late
            # report must not resurrect a StreamState nothing will pop,
            # nor pin item bytes the generator's one-shot free covered.
            return None
        oid = ObjectID(bytes(payload["oid"]))
        raw = payload.get("inline")
        if raw is not None:
            self.worker.store.put(oid, SerializedObject.from_bytes(raw))
        else:
            size = int(payload.get("size", 0))
            with self._lock:
                self._oid_owner[oid.binary()] = payload["node_client"]
                self._oid_sizes[oid.binary()] = size
            stream.known_remote_sizes[int(payload["idx"])] = size
        stream.commit(int(payload["idx"]))
        if tracing._TRACER is not None:
            ctx = tracing.extract(payload.get("trace"))
            if ctx is not None:
                tracing.event("stream.item", ctx=ctx,
                              idx=int(payload["idx"]),
                              node=payload["node_client"])
        self.owner_directory.publish_many([oid.binary()])
        return None

    def _stream_node(self, tid: TaskID):
        """(addr, client_id) of the node currently running a stream's
        producer, or (None, None)."""
        with self._lock:
            cid = self._task_node.get(tid) or self._task_target.get(tid)
            node = self._node_rec.get(cid) if cid else None
        if node is None and cid is not None:
            node = next((n for n in self.nodes()
                         if n["client_id"] == cid), None)
        return (self._node_addr(node) if node else None), cid

    def _send_stream_ack(self, tid: TaskID, n: int):
        """Coalesced, single-flight-per-task ack sender: only the LATEST
        consumption watermark matters, so a fast consumer costs one wire
        message per flush, not one per item."""
        with self._lock:
            cur = self._stream_ack_pending.get(tid, 0)
            self._stream_ack_pending[tid] = max(cur, n)
            if tid in self._stream_ack_inflight:
                return
            self._stream_ack_inflight.add(tid)
        self._prefetch_pool.submit(self._flush_stream_acks, tid)

    def _flush_stream_acks(self, tid: TaskID):
        while True:
            with self._lock:
                n = self._stream_ack_pending.pop(tid, None)
                if n is None:
                    self._stream_ack_inflight.discard(tid)
                    return
            self._stream_ctl(tid, ("stream_ack", tid.binary(), int(n)),
                             ("ack", tid.binary(), int(n)))

    def cancel_stream(self, tid: TaskID):
        """Generator dropped/closed consumer-side: cancel the in-flight
        producer task on its node (cooperative — the node's yield loop
        stops between yields) and release its stream state."""
        with self._lock:
            self._stream_tasks.discard(tid)
            self._stream_ack_pending.pop(tid, None)
        self._stream_ctl(tid, ("stream_cancel", tid.binary()),
                         ("cancel", tid.binary()))

    def _stream_ctl(self, tid: TaskID, direct_msg: tuple, pub_msg: tuple):
        addr, cid = self._stream_node(tid)
        if cid is None:
            return
        if addr is not None:
            try:
                self.head._peers.call(addr, direct_msg)
                return
            except Exception:  # noqa: BLE001 — fall back to the relay
                pass
        try:
            self.head.publish(f"stream|{cid}", pub_msg)
        except Exception:  # noqa: BLE001 — producer stays paused until
            pass           # the next watermark flush retries

    def handles(self, object_id: ObjectID) -> bool:
        with self._lock:
            tid = object_id.task_id()
            return tid in self.lineage or tid in self.external

    def prefetch(self, object_id: ObjectID, timeout: float = 30.0):
        """Background ensure_local with in-flight dedup: wait() polls may
        call this repeatedly without saturating the router pool."""
        with self._lock:
            if object_id in self._prefetching:
                return
            self._prefetching.add(object_id)

        def _run():
            try:
                self.ensure_local(object_id, timeout=timeout,
                                  _from_prefetch=True)
            except Exception:  # noqa: BLE001 — best-effort prefetch
                pass
            finally:
                with self._lock:
                    self._prefetching.discard(object_id)

        self._prefetch_pool.submit(_run)

    def ensure_local(self, object_id: ObjectID,
                     timeout: Optional[float] = None,
                     _from_prefetch: bool = False) -> None:
        """Block until a router-owned object's bytes are in the local
        store: wait on the completion event (with pull-polling so a
        missed task_done event cannot hang us), chunk-pull from the
        owning node, and re-execute from lineage if the owner died
        first. External (actor-task) results are never re-executed;
        their post-completion pull retries are BOUNDED by the owner's
        pin TTL — past it an ObjectLostError materializes into the
        store instead of ray_tpu.get hanging forever on evicted bytes."""
        from ray_tpu._private.serialization import SerializedObject
        from ray_tpu.exceptions import ObjectLostError

        deadline = None if timeout is None else time.monotonic() + timeout
        tid = object_id.task_id()
        external_deadline = None
        backoff = 0.05
        next_head_poll = time.monotonic() + 2.0
        while not self.worker.store.is_ready(object_id):
            if deadline is not None and time.monotonic() > deadline:
                raise GetTimeoutError(
                    f"remote object {object_id.hex()[:16]}… not available "
                    f"within timeout")
            with self._lock:
                ev = self._done.get(tid)
                exc = self._failed.get(tid)
            if exc is not None:
                return  # error already materialized into the store
            if not _from_prefetch:
                # A background prefetch is already transferring this
                # object: wait for it instead of starting a duplicate
                # full-byte pull (get() kicks off prefetches for the
                # whole ref list right before its foreground loop).
                with self._lock:
                    prefetching = object_id in self._prefetching
                if prefetching:
                    self.worker.store.wait([object_id], 1, timeout=0.25)
                    continue
            if ev is not None:
                # Event-driven completion wakeup; the bounded wait only
                # covers the missed-task_done case (head restart).
                ev.wait(timeout=0.5)
            # OWNER-table pull first: this driver owns the object and
            # learned its holder from the direct completion stream — the
            # transfer is p2p, zero head involvement.
            raw = None
            ob = object_id.binary()
            with self._lock:
                holder = self._oid_owner.get(ob)
            if holder is not None:
                addr = self._holder_addr(holder)
                if addr is not None:
                    raw = self.head._peers.pull_retrying(addr, ob)
                    if raw is not None:
                        with self._lock:
                            self.owner_table_pulls += 1
                if raw is None and self._client_alive(holder):
                    # Holder alive but not directly reachable (NAT,
                    # poisoned lanes): the head relays the bytes from
                    # the holder WE name — its directory is not
                    # consulted (the owner's table is the directory).
                    try:
                        raw = self.head.object_pull_from(holder, ob)
                    except RayTaskError as task_exc:
                        self.worker.store.put_error(object_id, task_exc)
                        return
                    except Exception as exc:  # noqa: BLE001 — head busy
                        log.debug("relay-from-holder pull failed: %r",
                                  exc)
                        raw = None
            done_now = ev is not None and ev.is_set()
            if raw is None and (done_now
                                or time.monotonic() >= next_head_poll):
                # Head FALLBACK directory: relay-path locations, lease-
                # transferred entries, and the missed-task_done edge
                # (head restart). While the producer is still running
                # this is throttled — a pending result must not turn
                # into a per-round head RPC.
                next_head_poll = time.monotonic() + 2.0
                try:
                    raw = self.head.object_pull(ob)
                except RayTaskError as task_exc:
                    # The owner's store holds the task's ERROR, not bytes
                    # — surface it instead of retrying a pull that can
                    # never produce data (belt-and-braces for a missed
                    # errs payload, e.g. across a head restart).
                    self.worker.store.put_error(object_id, task_exc)
                    return
                except Exception as exc:  # head hiccup: retry loop
                    log.debug("ensure_local pull failed; retrying: %r",
                              exc)
                    raw = None
            if raw is not None:
                self.worker.store.put(
                    object_id, SerializedObject.from_bytes(raw))
                return
            if ev is not None and ev.is_set():
                with self._lock:
                    external = tid in self.external
                    has_lineage = tid in self.lineage
                if not external and not has_lineage:
                    # Completed, owner can't serve the bytes, and there
                    # is no lineage spec to re-execute (lineage pinning
                    # off / spec dropped): unbounded pull retries can
                    # never converge — bound them like the external
                    # case and materialize a typed loss. Chaos-induced
                    # connection resets land here instead of spinning.
                    if external_deadline is None:
                        external_deadline = (
                            time.monotonic()
                            + GlobalConfig.external_pull_ttl_s)
                    elif time.monotonic() > external_deadline:
                        self.worker.store.put_error(
                            object_id, ObjectLostError(
                                f"object {object_id.hex()[:16]}… "
                                f"completed but its bytes are no longer "
                                f"served by any node and no lineage is "
                                f"pinned to reconstruct it"))
                        return
                    if self._stop.wait(backoff):
                        return  # router shutting down
                    # Jittered exponential backoff: concurrent pullers
                    # must not stampede a recovering owner in lockstep.
                    backoff = min(backoff * 2, 1.0)
                    continue
                if external:
                    # Actor-task result: never re-executed. The hosting
                    # node may still be serializing — retry with backoff;
                    # if the node died, the RemoteActorRuntime watcher
                    # materializes an ActorDiedError. If the node is
                    # alive but its pin TTL/cap evicted the bytes, every
                    # pull returns None forever — bound the retries and
                    # declare the object lost.
                    if external_deadline is None:
                        external_deadline = (
                            time.monotonic()
                            + GlobalConfig.external_pull_ttl_s)
                    elif time.monotonic() > external_deadline:
                        self.worker.store.put_error(
                            object_id, ObjectLostError(
                                f"remote actor-task result "
                                f"{object_id.hex()[:16]}… completed but "
                                f"its bytes are no longer served by the "
                                f"hosting node (result pin expired or "
                                f"evicted); actor tasks are not "
                                f"re-executed from lineage"))
                        return
                    if self._stop.wait(backoff):
                        return  # router shutting down
                    backoff = min(backoff * 2, 1.0)
                    continue
                # Task finished but its owner cannot serve the bytes:
                # the node died holding them. Re-execute from lineage.
                self._reexecute(tid)

    def _reexecute(self, tid: TaskID):
        with self._lock:
            spec = self.lineage.get(tid)
            if spec is None or tid in self._recovering:
                return
            self._recovering.add(tid)
            self._completed.discard(tid)  # re-executing: not done anymore
            ev = self._done.get(tid)
            if ev is not None:
                ev.clear()
            dead = self._task_node.pop(tid, None)
            if dead is not None:
                self._dec_inflight_locked(dead)
            # Result locations on the dead owner are stale now.
            for ob in [o.binary() for o in spec.return_ids]:
                self._oid_owner.pop(ob, None)
        # Recover args that lived on dead nodes first (transitive lineage).
        for ref in _collect_refs(spec.args, spec.kwargs):
            ob = ref.object_id.binary()
            with self._lock:
                owner = self._oid_owner.get(ob)
            if owner is not None and not self._client_alive(owner) \
                    and not self.worker.store.is_ready(ref.object_id):
                with self._lock:
                    self._oid_owner.pop(ob, None)
                self.ensure_local(ref.object_id, timeout=60.0)
        try:
            self._accept(spec, None, tried=(dead,) if dead else ())
        finally:
            with self._lock:
                self._recovering.discard(tid)

    # ------------------------------------------------------------- watcher
    def _watch_loop(self):
        """Re-route in-flight tasks off dead nodes (node failure
        detection: membership comes from the head's heartbeat monitor)."""
        while not self._stop.wait(0.5):
            with self._lock:
                parked = bool(self._parked)
                inflight = dict(self._task_node)
                actors = list(self.remote_actors)
            if parked:
                self._retry_parked()
            if not inflight and not actors:
                continue
            nodes = self.nodes(refresh=True)
            alive = {n["client_id"] for n in nodes if n.get("alive")}
            for rt in actors:
                try:
                    rt.check_node(alive)
                except Exception as exc:  # keep the watcher alive
                    log.warning("remote-actor liveness check failed; "
                                "watcher continues: %r", exc)
            with self._lock:
                self.remote_actors = [rt for rt in self.remote_actors
                                      if not rt.dead]
            if not inflight:
                continue
            for tid, client_id in inflight.items():
                if client_id in alive:
                    continue
                with self._lock:
                    spec = self.lineage.get(tid)
                    still_there = self._task_node.get(tid) == client_id
                    if still_there:
                        self._task_node.pop(tid, None)
                        self._dec_inflight_locked(client_id)
                if spec is None or not still_there:
                    continue
                if spec.attempt >= spec.max_retries:
                    # Retries exhausted (max_retries=0 tasks never
                    # replay): materialize the typed error — for a
                    # streaming task it lands on the end marker, so the
                    # consumer's next() raises instead of hanging.
                    self._fail(spec, WorkerCrashedError(
                        f"task {spec.name!r} was in flight on a node "
                        f"that died and max_retries={spec.max_retries} "
                        f"is exhausted"))
                    continue
                import dataclasses

                retry = dataclasses.replace(spec, attempt=spec.attempt + 1)
                self._accept(retry, None, tried=(client_id,))

    def shutdown(self):
        self._stop.set()
        # Lease handoff: directory entries that must outlive this owner
        # (bytes living on cluster nodes) transfer to the head's
        # fallback directory in ONE coalesced flight, so borrowers of a
        # gracefully-exited driver keep resolving. A SIGKILLed owner
        # skips this — its consumers fail typed (OwnerDiedError).
        if GlobalConfig.ownership_directory:
            try:
                entries = self.owner_directory.snapshot_locations()
                if entries:
                    self.head.object_transfer_many(entries)
            except Exception as exc:  # noqa: BLE001 — head gone: the
                log.debug("lease handoff failed (head unreachable); "
                          "borrowed refs will fail typed: %r", exc)
        with self._dispatch_cv:
            self._dispatch_cv.notify_all()
        self._pool.shutdown(wait=False, cancel_futures=True)
        self._prefetch_pool.shutdown(wait=False, cancel_futures=True)
