"""Driver-side router for pushing tasks onto remote node daemons.

Rebuild of the reference's cross-node scheduling path (reference roles:
owner-side lease requests spilling to remote raylets + the object
directory/ObjectManager pull protocol [unverified]). A driver attached to
a head service sees the registered node daemons (``node_daemon.py``) and
routes tasks onto them when:

- the task's resource demand is **infeasible locally** (e.g. a custom
  resource only a remote node offers), or
- an explicit ``NodeAffinitySchedulingStrategy`` targets a daemon node, or
- the local backlog passes the spill threshold and a feasible node is
  less loaded (hybrid pack-then-spill, same policy family as
  ``cluster_utils.ClusterScheduler``).

Data stays off the driver where possible: ref args whose values live on
a node travel as *pull refs* — the executing node pulls the serialized
bytes head-relayed (chunked) from the owning node, so a chain of remote
tasks scheduled onto one node never round-trips the driver. Results stay
on the producing node until a consumer (driver ``get`` or another node)
actually pulls them.

Failure story: the router keeps the TaskSpec lineage of everything it
pushed. A node SIGKILL surfaces as a dead membership entry; in-flight
tasks re-route to surviving feasible nodes, and lost not-yet-pulled
result objects are re-executed from lineage on demand (ObjectRecovery
parity across real OS-process nodes).
"""

from __future__ import annotations

import pickle
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.scheduler import TaskSpec, _collect_refs
from ray_tpu.exceptions import RayTaskError, WorkerCrashedError
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

_NODES_TTL_S = 0.5


class RemoteRouter:
    def __init__(self, worker):
        self.worker = worker
        self.head = worker.head_client
        self.head.handlers["task_done"] = self._on_task_done
        self.lineage: Dict[TaskID, TaskSpec] = {}
        self._done: Dict[TaskID, threading.Event] = {}
        self._task_node: Dict[TaskID, str] = {}   # -> node client_id
        self._inflight: Dict[str, int] = {}       # node client -> pushed
        self._oid_owner: Dict[bytes, str] = {}    # done oids -> node client
        self._failed: Dict[TaskID, BaseException] = {}
        # Remote ACTOR tasks: completion tracked here (task_done +
        # object pull), but never re-executed from lineage — interrupted
        # actor calls fail (reference restart semantics); the
        # RemoteActorRuntime's watcher materializes the errors.
        self.external: Dict[TaskID, str] = {}     # tid -> node client_id
        self.remote_actors: List = []             # RemoteActorRuntime watch
        self._spread_counter = 0
        self._placed_counts: Dict[str, int] = {}  # node -> actors placed
        # Demand surface for the autoscaler: tasks no current node (and
        # no local capacity) can run are PARKED here until membership
        # changes; their shapes ride the driver's heartbeat status so
        # the autoscaler can provision nodes that fit (reference:
        # resource_demand in the raylet's load report).
        self._parked: List[TaskSpec] = []
        self._unmet_hints: List[tuple] = []  # (shape, ts) — actor asks
        if self.head.status_fn is None:
            self.head.status_fn = self._status
        self._recovering: set = set()
        self._prefetching: set = set()
        self._lock = threading.Lock()
        self._nodes_cache: tuple = (0.0, [])
        self._pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="ray_tpu_router")
        # Prefetches block inside ensure_local (up to their timeout) —
        # they get their OWN pool so queued task pushes and lineage
        # re-execution on self._pool never starve behind them.
        self._prefetch_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="ray_tpu_router_prefetch")
        self._stop = threading.Event()
        self._watcher = threading.Thread(
            target=self._watch_loop, daemon=True, name="ray_tpu_router_watch")
        self._watcher.start()

    # ------------------------------------------------------------- routing
    def nodes(self, refresh: bool = False) -> List[dict]:
        now = time.monotonic()
        ts, cached = self._nodes_cache
        if not refresh and now - ts < _NODES_TTL_S:
            return cached
        try:
            nodes = self.head.node_list()
        except Exception:  # noqa: BLE001 — head unreachable: no routing
            nodes = []
        self._nodes_cache = (now, nodes)
        return nodes

    @staticmethod
    def _fits(node: dict, demand: Dict[str, float]) -> bool:
        res = node.get("resources") or {}
        return all(res.get(k, 0.0) >= v for k, v in demand.items())

    def _choose_node(self, spec: TaskSpec,
                     exclude: tuple = ()) -> Optional[dict]:
        nodes = [n for n in self.nodes()
                 if n.get("alive") and n["client_id"] not in exclude]
        strat = spec.scheduling_strategy
        if isinstance(strat, NodeAffinitySchedulingStrategy):
            for n in nodes:
                if n.get("node_id") == strat.node_id:
                    return n
            if not getattr(strat, "soft", False):
                return None
            # Soft affinity: target gone, fall through to least-loaded.
        feasible = [n for n in nodes if self._fits(n, spec.resources)]
        if not feasible:
            return None
        return min(feasible, key=self._load)

    def _load(self, n: dict) -> float:
        """Reported backlog (heartbeat, ~0.5 s stale) plus locally-known
        in-flight pushes, so a burst submitted between heartbeats spreads
        instead of piling onto one node."""
        status = n.get("status") or {}
        cpus = max((n.get("resources") or {}).get("CPU", 1.0), 1.0)
        with self._lock:
            inflight = self._inflight.get(n["client_id"], 0)
        return (float(status.get("backlog", 0)) + inflight) / cpus

    # ------------------------------------------------------ actor placement
    @staticmethod
    def actor_demand(opts: dict) -> Dict[str, float]:
        """Resource demand of an actor from its options (num_cpus +
        custom resources + PG bundle shape)."""
        demand: Dict[str, float] = {}
        if opts.get("num_cpus"):
            demand["CPU"] = float(opts["num_cpus"])
        strat = opts.get("scheduling_strategy")
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
        )

        if isinstance(strat, PlacementGroupSchedulingStrategy):
            # PG-aware placement: the bundle's resource shape is the
            # demand; the PG itself reserves per-node capacity only in
            # the sim plane, so here bundles steer feasibility.
            pg = strat.placement_group
            idx = strat.placement_group_bundle_index
            bundles = getattr(pg, "bundles", None) or []
            if bundles:
                bundle = bundles[max(idx, 0) % len(bundles)]
                demand.update({k: float(v) for k, v in bundle.items()})
        demand.update({k: float(v)
                       for k, v in (opts.get("resources") or {}).items()})
        return demand

    def place_actor(self, opts: dict) -> Optional[dict]:
        """Placement decision for a new actor (GcsActorScheduler role).
        Returns the hosting node's membership record, or None for a
        driver-local actor. Same policy family as maybe_route:

        - ``NodeAffinitySchedulingStrategy`` pins to that node;
        - a resource demand infeasible locally goes to a feasible node
          (loud error when none exists);
        - ``scheduling_strategy="SPREAD"`` round-robins over the local
          runtime + all feasible nodes;
        - thin clients (``ray://``) always place on the cluster;
        - otherwise the actor stays local (driver-owned, zero latency).
        """
        demand = self.actor_demand(opts)
        strat = opts.get("scheduling_strategy")
        nodes = [n for n in self.nodes(refresh=True) if n.get("alive")]
        client_mode = getattr(self.worker, "client_mode", False)
        if isinstance(strat, NodeAffinitySchedulingStrategy):
            if strat.node_id == self.worker.node_id.hex() \
                    and not client_mode:
                return None
            for n in nodes:
                if n.get("node_id") == strat.node_id:
                    return n
            if not getattr(strat, "soft", False):
                raise ValueError(
                    f"no alive node {strat.node_id!r} for actor "
                    f"NodeAffinity placement")
        feasible = [n for n in nodes if self._fits(n, demand)]
        local_fits = (self.worker.resource_pool.fits(demand)
                      and not client_mode)
        if not local_fits:
            if not feasible:
                # Record the shape so an autoscaler can provision for a
                # retry, then fail loudly (actor creation is synchronous
                # — it cannot park like a task).
                with self._lock:
                    self._unmet_hints.append((dict(demand),
                                              time.monotonic()))
                raise ValueError(
                    f"actor resource demand {demand} is infeasible: no "
                    f"local capacity and no feasible cluster node")
            return self._record_placement(
                min(feasible, key=self._actor_load))
        if strat == "SPREAD" and feasible:
            # Round-robin across local + feasible nodes so replica/worker
            # groups land on every machine.
            with self._lock:
                slot = self._spread_counter
                self._spread_counter += 1
            candidates: List[Optional[dict]] = [None] + feasible
            return self._record_placement(
                candidates[slot % len(candidates)])
        return None

    def _record_placement(self, node: Optional[dict]) -> Optional[dict]:
        """Count placements locally so a burst placed between heartbeats
        spreads instead of piling onto one node (same trick as the task
        router's in-flight counter)."""
        if node is not None:
            with self._lock:
                cid = node["client_id"]
                self._placed_counts[cid] = \
                    self._placed_counts.get(cid, 0) + 1
        return node

    def _actor_load(self, n: dict) -> float:
        status = n.get("status") or {}
        with self._lock:
            placed = self._placed_counts.get(n["client_id"], 0)
        # The heartbeat-reported count eventually includes our local
        # placements; take the max so they are not double-counted.
        return max(float(status.get("actors", 0)), float(placed)) \
            + self._load(n)

    def register_external(self, tid: TaskID, node_client: str):
        """Track a remote actor task: completion arrives via task_done;
        the result oids resolve through ensure_local like routed tasks."""
        with self._lock:
            self.external[tid] = node_client
            self._done.setdefault(tid, threading.Event())

    def watch_remote_actor(self, runtime):
        """Register a RemoteActorRuntime for node-death watching (fail
        in-flight calls + restart-on-surviving-node)."""
        with self._lock:
            self.remote_actors.append(runtime)

    # --------------------------------------------------------- demand report
    def unmet_shapes(self) -> List[Dict[str, float]]:
        """Resource shapes this driver wants but no current node serves
        (parked tasks + recent infeasible actor asks) — the autoscaler's
        scale-up signal."""
        now = time.monotonic()
        with self._lock:
            self._unmet_hints = [(s, ts) for s, ts in self._unmet_hints
                                 if now - ts < 30.0]
            return [dict(s.resources) for s in self._parked] + \
                [dict(s) for s, _ in self._unmet_hints]

    def _status(self) -> dict:
        return {
            "backlog": self.worker.scheduler.backlog_size(),
            "unmet": self.unmet_shapes(),
        }

    def _retry_parked(self):
        with self._lock:
            parked, self._parked = self._parked, []
        still = []
        for spec in parked:
            node = self._choose_node(spec)
            if node is None:
                still.append(spec)
            else:
                self._accept(spec, node)
        if still:
            with self._lock:
                self._parked = still + self._parked

    def maybe_route(self, spec: TaskSpec) -> bool:
        """Called by Worker.submit_task before local submission. Returns
        True iff the task was taken over for remote execution."""
        strat = spec.scheduling_strategy
        affinity_remote = (
            isinstance(strat, NodeAffinitySchedulingStrategy)
            and any(n.get("node_id") == strat.node_id
                    for n in self.nodes()))
        local_fits = (self.worker.resource_pool.fits(spec.resources)
                      and not getattr(self.worker, "client_mode", False))
        spill = False
        if local_fits and not affinity_remote:
            backlog = self.worker.scheduler.backlog_size()
            cpus = max(
                self.worker.resource_pool.total.get("CPU", 1.0), 1.0)
            spill = backlog / cpus > GlobalConfig.spill_backlog_factor
        if not (affinity_remote or not local_fits or spill):
            return False
        node = self._choose_node(spec)
        if node is None:
            hard_affinity = (isinstance(strat, NodeAffinitySchedulingStrategy)
                            and not getattr(strat, "soft", False))
            if not local_fits and not hard_affinity \
                    and not getattr(self.worker, "client_mode", False):
                # Infeasible EVERYWHERE: park it and advertise the shape
                # so an autoscaler can provision a node that fits; the
                # watch loop retries when membership changes. (Thin
                # clients keep their loud no-capacity error; a hard
                # NodeAffinity miss is a strategy miss, not a resource
                # shape an autoscaler could satisfy — don't park it.)
                with self._lock:
                    self._parked.append(spec)
                    self.lineage[spec.task_id] = spec
                    self._done.setdefault(spec.task_id, threading.Event())
                return True
            return False
        if not local_fits or affinity_remote or self._node_less_loaded(node):
            self._accept(spec, node)
            return True
        return False

    def _node_less_loaded(self, node: dict) -> bool:
        status = node.get("status") or {}
        cpus = max((node.get("resources") or {}).get("CPU", 1.0), 1.0)
        local_cpus = max(
            self.worker.resource_pool.total.get("CPU", 1.0), 1.0)
        return (float(status.get("backlog", 0)) / cpus
                < self.worker.scheduler.backlog_size() / local_cpus)

    def _accept(self, spec: TaskSpec, node: dict):
        with self._lock:
            self.lineage[spec.task_id] = spec
            self._done.setdefault(spec.task_id, threading.Event())
        self._pool.submit(self._push_safely, spec, node)

    # ---------------------------------------------------------------- push
    def _push_safely(self, spec: TaskSpec, node: Optional[dict],
                     exclude: tuple = ()):
        try:
            self._push(spec, node, exclude)
        except Exception as exc:  # noqa: BLE001 — routing failure boundary
            self._fail(spec, exc)

    def _fail(self, spec: TaskSpec, exc: BaseException):
        if not isinstance(exc, (RayTaskError, WorkerCrashedError)):
            exc = RayTaskError.from_exception(spec.name, exc)
        for oid in spec.return_ids:
            self.worker.store.put_error(oid, exc)
        with self._lock:
            self._failed[spec.task_id] = exc
            ev = self._done.get(spec.task_id)
        if ev is not None:
            ev.set()

    def _push(self, spec: TaskSpec, node: Optional[dict],
              exclude: tuple = ()):
        import cloudpickle

        ctx = self.worker.serialization_context
        # Wait for ref args to be *produced* (locally ready, or remotely
        # done) before shipping; values the driver has inline, values on a
        # node travel as pull-refs the executor resolves node-side.
        deps = _collect_refs(spec.args, spec.kwargs)
        for ref in deps:
            self._await_dep(ref.object_id)

        def _wire_arg(v):
            from ray_tpu._private.worker import ObjectRef

            if not isinstance(v, ObjectRef):
                return ("v", ctx.serialize(v).to_bytes())
            ob = v.object_id.binary()
            with self._lock:
                owner = self._oid_owner.get(ob)
            if owner is None or not self._client_alive(owner):
                # Driver-local (or recovered-to-driver) value: inline it.
                value = self.worker.get_object(v)
                return ("v", ctx.serialize(value).to_bytes())
            return ("r", ob)

        payload = pickle.dumps({
            "driver_id": self.head.client_id,
            "task_id": spec.task_id.binary(),
            "return_ids": [o.binary() for o in spec.return_ids],
            "num_returns": spec.num_returns,
            "name": spec.name,
            "resources": spec.resources,
            "max_retries": spec.max_retries,
            "retry_exceptions": spec.retry_exceptions,
            "runtime_env": spec.runtime_env,
            "fn": cloudpickle.dumps(spec.function),
            "args": [_wire_arg(a) for a in spec.args],
            "kwargs": {k: _wire_arg(v) for k, v in spec.kwargs.items()},
        }, protocol=5)
        last_exc: Optional[BaseException] = None
        tried = list(exclude)
        for _ in range(3):
            if node is None:
                node = self._choose_node(spec, exclude=tuple(tried))
            if node is None:
                break
            cid = node["client_id"]
            with self._lock:
                self._task_node[spec.task_id] = cid
                self._inflight[cid] = self._inflight.get(cid, 0) + 1
            try:
                self.head.task_push(cid, payload)
                return
            except Exception as exc:  # noqa: BLE001 — node unreachable
                last_exc = exc
                tried.append(cid)
                node = None
                with self._lock:
                    self._task_node.pop(spec.task_id, None)
                    self._dec_inflight_locked(cid)
        raise WorkerCrashedError(
            f"no reachable node accepted task {spec.name!r}"
            + (f" (last error: {last_exc})" if last_exc else ""))

    def _await_dep(self, object_id: ObjectID, timeout: float = 300.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.worker.store.is_ready(object_id):
                return
            tid = object_id.task_id()
            with self._lock:
                ev = self._done.get(tid)
            if ev is not None:
                if ev.wait(timeout=min(1.0, deadline - time.monotonic())):
                    with self._lock:
                        exc = self._failed.get(tid)
                    if exc is not None:
                        raise exc
                    return
                continue
            # Locally-produced dep: poll the store.
            ready, _ = self.worker.store.wait(
                [object_id], 1, timeout=min(0.5, deadline - time.monotonic()))
            if ready:
                return
        raise TimeoutError(
            f"dependency {object_id.hex()[:16]}… not produced in time")

    def _client_alive(self, client_id: str) -> bool:
        return any(n["client_id"] == client_id and n.get("alive")
                   for n in self.nodes())

    # ----------------------------------------------------------- completion
    def _dec_inflight_locked(self, cid: str):
        n = self._inflight.get(cid, 0) - 1
        if n <= 0:
            self._inflight.pop(cid, None)
        else:
            self._inflight[cid] = n

    def _on_task_done(self, event: tuple):
        payload = pickle.loads(event[1])
        tid = TaskID(payload["task_id"])
        with self._lock:
            for ob in payload["oid_bins"]:
                self._oid_owner[ob] = payload["node_client"]
            cid = self._task_node.pop(tid, None)
            if cid is not None:
                self._dec_inflight_locked(cid)
            ev = self._done.setdefault(tid, threading.Event())
        ev.set()
        return None

    def handles(self, object_id: ObjectID) -> bool:
        with self._lock:
            tid = object_id.task_id()
            return tid in self.lineage or tid in self.external

    def prefetch(self, object_id: ObjectID, timeout: float = 30.0):
        """Background ensure_local with in-flight dedup: wait() polls may
        call this repeatedly without saturating the router pool."""
        with self._lock:
            if object_id in self._prefetching:
                return
            self._prefetching.add(object_id)

        def _run():
            try:
                self.ensure_local(object_id, timeout=timeout,
                                  _from_prefetch=True)
            except Exception:  # noqa: BLE001 — best-effort prefetch
                pass
            finally:
                with self._lock:
                    self._prefetching.discard(object_id)

        self._prefetch_pool.submit(_run)

    def ensure_local(self, object_id: ObjectID,
                     timeout: Optional[float] = None,
                     _from_prefetch: bool = False) -> None:
        """Block until a router-owned object's bytes are in the local
        store: wait on the completion event (with pull-polling so a
        missed task_done event cannot hang us), chunk-pull from the
        owning node, and re-execute from lineage if the owner died
        first. External (actor-task) results are never re-executed;
        their post-completion pull retries are BOUNDED by the owner's
        pin TTL — past it an ObjectLostError materializes into the
        store instead of ray_tpu.get hanging forever on evicted bytes."""
        from ray_tpu._private.serialization import SerializedObject
        from ray_tpu.exceptions import ObjectLostError

        deadline = None if timeout is None else time.monotonic() + timeout
        tid = object_id.task_id()
        external_deadline = None
        backoff = 0.05
        while not self.worker.store.is_ready(object_id):
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"remote object {object_id.hex()[:16]}… not available "
                    f"within timeout")
            with self._lock:
                ev = self._done.get(tid)
                exc = self._failed.get(tid)
            if exc is not None:
                return  # error already materialized into the store
            if not _from_prefetch:
                # A background prefetch is already transferring this
                # object: wait for it instead of starting a duplicate
                # full-byte pull (get() kicks off prefetches for the
                # whole ref list right before its foreground loop).
                with self._lock:
                    prefetching = object_id in self._prefetching
                if prefetching:
                    self.worker.store.wait([object_id], 1, timeout=0.25)
                    continue
            if ev is not None:
                # Event-driven completion wakeup; the bounded wait only
                # covers the missed-task_done case (head restart).
                ev.wait(timeout=0.5)
            # Pull unconditionally each round: the head's object directory
            # knows completed results even if this driver missed the
            # task_done event (e.g. across a head restart).
            raw = None
            try:
                raw = self.head.object_pull(object_id.binary())
            except Exception:  # noqa: BLE001 — head hiccup: retry loop
                raw = None
            if raw is not None:
                self.worker.store.put(
                    object_id, SerializedObject.from_bytes(raw))
                return
            if ev is not None and ev.is_set():
                with self._lock:
                    external = tid in self.external
                if external:
                    # Actor-task result: never re-executed. The hosting
                    # node may still be serializing — retry with backoff;
                    # if the node died, the RemoteActorRuntime watcher
                    # materializes an ActorDiedError. If the node is
                    # alive but its pin TTL/cap evicted the bytes, every
                    # pull returns None forever — bound the retries and
                    # declare the object lost.
                    if external_deadline is None:
                        external_deadline = (
                            time.monotonic()
                            + GlobalConfig.external_pull_ttl_s)
                    elif time.monotonic() > external_deadline:
                        self.worker.store.put_error(
                            object_id, ObjectLostError(
                                f"remote actor-task result "
                                f"{object_id.hex()[:16]}… completed but "
                                f"its bytes are no longer served by the "
                                f"hosting node (result pin expired or "
                                f"evicted); actor tasks are not "
                                f"re-executed from lineage"))
                        return
                    if self._stop.wait(backoff):
                        return  # router shutting down
                    backoff = min(backoff * 2, 1.0)
                    continue
                # Task finished but its owner cannot serve the bytes:
                # the node died holding them. Re-execute from lineage.
                self._reexecute(tid)

    def _reexecute(self, tid: TaskID):
        with self._lock:
            spec = self.lineage.get(tid)
            if spec is None or tid in self._recovering:
                return
            self._recovering.add(tid)
            ev = self._done.get(tid)
            if ev is not None:
                ev.clear()
            dead = self._task_node.pop(tid, None)
            if dead is not None:
                self._dec_inflight_locked(dead)
            # Result locations on the dead owner are stale now.
            for ob in [o.binary() for o in spec.return_ids]:
                self._oid_owner.pop(ob, None)
        # Recover args that lived on dead nodes first (transitive lineage).
        for ref in _collect_refs(spec.args, spec.kwargs):
            ob = ref.object_id.binary()
            with self._lock:
                owner = self._oid_owner.get(ob)
            if owner is not None and not self._client_alive(owner) \
                    and not self.worker.store.is_ready(ref.object_id):
                with self._lock:
                    self._oid_owner.pop(ob, None)
                self.ensure_local(ref.object_id, timeout=60.0)
        try:
            self._push_safely(spec, None,
                              exclude=(dead,) if dead else ())
        finally:
            with self._lock:
                self._recovering.discard(tid)

    # ------------------------------------------------------------- watcher
    def _watch_loop(self):
        """Re-route in-flight tasks off dead nodes (node failure
        detection: membership comes from the head's heartbeat monitor)."""
        while not self._stop.wait(0.5):
            with self._lock:
                parked = bool(self._parked)
                inflight = dict(self._task_node)
                actors = list(self.remote_actors)
            if parked:
                self._retry_parked()
            if not inflight and not actors:
                continue
            nodes = self.nodes(refresh=True)
            alive = {n["client_id"] for n in nodes if n.get("alive")}
            for rt in actors:
                try:
                    rt.check_node(alive)
                except Exception:  # noqa: BLE001 — keep the watcher alive
                    pass
            with self._lock:
                self.remote_actors = [rt for rt in self.remote_actors
                                      if not rt.dead]
            if not inflight:
                continue
            for tid, client_id in inflight.items():
                if client_id in alive:
                    continue
                with self._lock:
                    spec = self.lineage.get(tid)
                    still_there = self._task_node.get(tid) == client_id
                    if still_there:
                        self._task_node.pop(tid, None)
                        self._dec_inflight_locked(client_id)
                if spec is None or not still_there:
                    continue
                retry = TaskSpec(
                    task_id=spec.task_id, function=spec.function,
                    args=spec.args, kwargs=spec.kwargs,
                    num_returns=spec.num_returns,
                    return_ids=spec.return_ids, name=spec.name,
                    resources=spec.resources, max_retries=spec.max_retries,
                    retry_exceptions=spec.retry_exceptions,
                    scheduling_strategy=spec.scheduling_strategy,
                    attempt=spec.attempt + 1)
                with self._lock:
                    self.lineage[tid] = retry
                self._push_safely(retry, None, exclude=(client_id,))

    def shutdown(self):
        self._stop.set()
        self._pool.shutdown(wait=False, cancel_futures=True)
        self._prefetch_pool.shutdown(wait=False, cancel_futures=True)
