"""Node daemon: joins this machine's worker pool to a head service.

Rebuild of the reference's per-node daemon role (reference: the raylet —
node registration with the GCS, a local worker pool + scheduler executing
leased tasks, and an object manager serving its store's objects to peers
[unverified]). ``ray-tpu start --address=head:port`` runs one of these:

- boots a full local runtime (object store, worker-process pool, local
  scheduler) exactly like a driver, minus any application code;
- registers its node id + resource spec with the head's membership;
- heartbeats its load (scheduler backlog) so drivers' routers can spill
  to the least-loaded feasible node;
- serves ``task_push`` events: unpacks the wire task, pulls any ref args
  it doesn't hold (head-relayed chunked pull from the owning node — the
  driver stays out of the data path), executes through the normal local
  scheduler (worker processes, retries, OOM kill), then reports
  ``task_done`` with the result object ids — the bytes stay here until
  someone pulls them;
- serves chunked ``object_meta``/``object_chunk`` reads from its store
  via the shared HeadClient event machinery.

Kill it with SIGKILL and the head's heartbeat monitor declares the node
dead; drivers re-route in-flight work and re-execute lost results from
lineage (tested in tests/test_multinode.py).
"""

from __future__ import annotations

import argparse
import json
import pickle
import signal
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict

from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.scheduler import TaskSpec


def prefetch_serialized(pull_fn: Callable[[bytes], Any], oid_bins: list,
                        pool: ThreadPoolExecutor) -> Dict[bytes, Any]:
    """Pull many objects' serialized bytes CONCURRENTLY (pipelined
    argument prefetch): every pull starts before any finishes, so a
    task's dispatch overlaps its transfers instead of serializing
    behind them. Returns {oid_bin: raw_or_None}; a pull that raised
    maps to its exception (the caller decides per-arg)."""
    futures = {ob: pool.submit(pull_fn, ob) for ob in dict.fromkeys(oid_bins)}
    out: Dict[bytes, Any] = {}
    for ob, fut in futures.items():
        try:
            out[ob] = fut.result()
        except BaseException as exc:  # noqa: BLE001 — per-arg failure
            out[ob] = exc
    return out


class NodeDaemon:
    def __init__(self, address: str, num_cpus: int = 2,
                 resources: Dict[str, float] | None = None,
                 worker_mode: str | None = None):
        import ray_tpu
        from ray_tpu._private.worker import global_worker

        ray_tpu.init(num_cpus=num_cpus, resources=resources,
                     worker_mode=worker_mode, address=address)
        self.worker = global_worker()
        self.head = self.worker.head_client
        self.head.handlers["task_push"] = self._on_task_push
        self.head.status_fn = self._status
        # Cluster actor plane: host actors placed here by remote drivers
        # (direct actor_op requests + head-relayed actor_push fallback).
        from ray_tpu._private.remote_actor import ActorHost

        self.actor_host = ActorHost(self.worker, self.head)
        self.head.node_register(
            self.worker.node_id.hex(), self.worker.resource_pool.total)
        # Bounded pools replace the old thread-per-pushed-task model:
        # _intake unpacks + prefetches args + submits; _pulls runs the
        # concurrent argument pulls; _reporter ships task_done RPCs
        # (which coalesce into batch frames at the head client).
        self._intake = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="ray_tpu_node_intake")
        self._pulls = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="ray_tpu_node_pull")
        self._reporter = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="ray_tpu_node_done")
        # Pushed-task function cache: a fan-out ships the SAME pickled
        # function N times; deserialize it once per digest. Byte-capped
        # LRU (pickle size as the weight proxy) so many distinct
        # functions with fat closures can't pin unbounded memory.
        from collections import OrderedDict

        self._fn_cache: "OrderedDict[bytes, tuple]" = OrderedDict()
        self._fn_cache_bytes = 0
        self._fn_cache_cap = 64 << 20
        self._fn_lock = threading.Lock()
        self._stop = threading.Event()

    def _load_fn(self, fn_bytes: bytes):
        import hashlib

        import cloudpickle

        key = hashlib.sha256(fn_bytes).digest()
        with self._fn_lock:
            hit = self._fn_cache.get(key)
            if hit is not None:
                self._fn_cache.move_to_end(key)
                return hit[0]
        fn = cloudpickle.loads(fn_bytes)
        with self._fn_lock:
            if key not in self._fn_cache:
                self._fn_cache[key] = (fn, len(fn_bytes))
                self._fn_cache_bytes += len(fn_bytes)
            while self._fn_cache_bytes > self._fn_cache_cap \
                    and len(self._fn_cache) > 1:
                _, (_, nbytes) = self._fn_cache.popitem(last=False)
                self._fn_cache_bytes -= nbytes
        return fn

    def _status(self) -> dict:
        hosted = sum(1 for a in self.worker.actors.values()
                     if not getattr(a, "borrower", False))
        router = self.worker.remote_router
        return {
            "backlog": self.worker.scheduler.backlog_size(),
            "available": self.worker.resource_pool.available(),
            "actors": hosted,  # borrowed handles are not load
            "unmet": router.unmet_shapes() if router is not None else [],
        }

    # ----------------------------------------------------------- task serve
    def _on_task_push(self, event: tuple):
        payload = pickle.loads(event[1])
        self._intake.submit(self._start_task, payload)
        return "accepted"

    def _ensure_object(self, oid_bin: bytes):
        """Materialize one pull-ref's bytes into the local store."""
        from ray_tpu._private.serialization import SerializedObject

        oid = ObjectID(bytes(oid_bin))
        if not self.worker.store.is_ready(oid):
            raw = self.head.object_pull(oid.binary())
            if raw is None:
                raise ValueError(
                    f"pull-ref {oid.hex()[:16]}… has no live owner")
            self.worker.store.put(oid, SerializedObject.from_bytes(raw))

    def _unwire_arg(self, wired: tuple) -> Any:
        from ray_tpu._private.serialization import SerializedObject

        kind, data = wired
        if kind == "v":
            return self.worker.serialization_context.deserialize(
                SerializedObject.from_bytes(data))
        # Pull-ref: prefetched into the store by _start_task.
        oid = ObjectID(bytes(data))
        self._ensure_object(oid.binary())  # no-op when prefetch landed it
        serialized = self.worker.store.get(oid)
        return self.worker.serialization_context.deserialize(serialized)

    def _start_task(self, payload: dict):
        """Unpack a pushed task, prefetch its remote args in parallel,
        submit to the local scheduler, and report completion from the
        store's ready callbacks — no blocking wait, no per-task thread
        (event-driven dispatch end to end)."""
        return_ids = [ObjectID(bytes(b)) for b in payload["return_ids"]]
        try:
            fn = self._load_fn(payload["fn"])
            wired = list(payload["args"]) + list(payload["kwargs"].values())
            pull_bins = [bytes(d) for k, d in wired if k == "r"]
            if pull_bins:
                prefetched = prefetch_serialized(
                    self._ensure_object, pull_bins, self._pulls)
                for exc in prefetched.values():
                    if isinstance(exc, BaseException):
                        raise exc
            args = tuple(self._unwire_arg(a) for a in payload["args"])
            kwargs = {k: self._unwire_arg(v)
                      for k, v in payload["kwargs"].items()}
            spec = TaskSpec(
                task_id=TaskID(bytes(payload["task_id"])),
                function=fn, args=args, kwargs=kwargs,
                num_returns=payload["num_returns"],
                return_ids=return_ids,
                name=payload["name"],
                resources=dict(payload["resources"]),
                max_retries=payload["max_retries"],
                retry_exceptions=payload["retry_exceptions"],
                runtime_env=payload.get("runtime_env"))
            self.worker.scheduler.submit(spec)
        except BaseException as exc:  # noqa: BLE001 — report, don't die
            from ray_tpu.exceptions import RayTaskError

            err = exc if isinstance(exc, RayTaskError) else \
                RayTaskError.from_exception(payload.get("name", "task"), exc)
            for oid in return_ids:
                if not self.worker.store.is_ready(oid):
                    self.worker.store.put_error(oid, err)
        # Completion rides the store's ready callbacks (errors also
        # materialize as ready): when the LAST output lands, report
        # task_done from the reporter pool — the RPC itself coalesces
        # into the head client's batch frames.
        remaining = [len(return_ids)]
        lock = threading.Lock()

        def _one_ready():
            with lock:
                remaining[0] -= 1
                if remaining[0] != 0:
                    return
            self._reporter.submit(self._report_done, payload, return_ids)

        for oid in return_ids:
            self.worker.store.on_ready(oid, _one_ready)

    def _report_done(self, payload: dict, return_ids: list):
        done = pickle.dumps({
            "task_id": bytes(payload["task_id"]),
            "oid_bins": [o.binary() for o in return_ids],
            "node_client": self.head.client_id,
        }, protocol=5)
        try:
            self.head.task_done(
                payload["driver_id"], [o.binary() for o in return_ids],
                done)
        except Exception:  # noqa: BLE001 — driver gone: results stay local
            pass

    # -------------------------------------------------------------- lifecycle
    def run_forever(self):
        signal.signal(signal.SIGTERM, lambda *_: self._stop.set())
        try:
            while not self._stop.wait(0.5):
                pass
        except KeyboardInterrupt:
            pass
        self.shutdown()

    def shutdown(self):
        import ray_tpu

        self._stop.set()
        for pool in (self._intake, self._pulls, self._reporter):
            pool.shutdown(wait=False, cancel_futures=True)
        self.actor_host.shutdown()
        ray_tpu.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--address", required=True, help="head host:port")
    ap.add_argument("--num-cpus", type=int, default=2)
    ap.add_argument("--resources", default="{}",
                    help='extra resources, e.g. \'{"accel": 1}\'')
    ap.add_argument("--worker-mode", default=None,
                    choices=(None, "process", "thread"))
    args = ap.parse_args(argv)
    daemon = NodeDaemon(
        args.address, num_cpus=args.num_cpus,
        resources=json.loads(args.resources),
        worker_mode=args.worker_mode)
    print(f"ray_tpu node {daemon.worker.node_id.hex()[:16]} joined "
          f"{args.address} as {daemon.head.client_id}", flush=True)
    daemon.run_forever()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
