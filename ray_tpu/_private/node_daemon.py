"""Node daemon: joins this machine's worker pool to a head service.

Rebuild of the reference's per-node daemon role (reference: the raylet —
node registration with the GCS, a local worker pool + scheduler executing
leased tasks, and an object manager serving its store's objects to peers
[unverified]). ``ray-tpu start --address=head:port`` runs one of these:

- boots a full local runtime (object store, worker-process pool, local
  scheduler) exactly like a driver, minus any application code;
- registers its node id + resource spec with the head's membership;
- heartbeats its load (scheduler backlog) so drivers' routers can spill
  to the least-loaded feasible node;
- serves ``task_push`` on TWO planes: the driver-dialed DIRECT plane
  (this node's object/request server — batched framed pushes, the head
  out of steady-state dispatch) and the head-relayed fallback (NAT'd
  drivers). Either way the daemon unpacks the wire task, pulls any ref
  args it doesn't hold (peer-to-peer chunked pull from the owning node,
  waiting out pending pull-refs whose producer hasn't finished yet — the
  owner-side barrier lives here, not on the driver), executes through
  the normal local scheduler (worker processes, retries, OOM kill), then
  reports ``task_done`` with the result object ids, their sizes (the
  drivers' locality scoring input) and any task errors — the bytes stay
  here until someone pulls them;
- caches pushed functions by content digest: a driver ships
  ``cloudpickle.dumps(fn)`` once per (node, digest) and references the
  digest thereafter; an unknown digest answers ``need_fn`` so the driver
  reships bytes (cache eviction / daemon restart recovery);
- serves chunked ``object_meta``/``object_chunk`` reads from its store
  via the shared HeadClient event machinery.

Kill it with SIGKILL and the head's heartbeat monitor declares the node
dead; drivers re-route in-flight work and re-execute lost results from
lineage (tested in tests/test_multinode.py).
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import signal
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict

from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.log import get_logger
from ray_tpu._private.scheduler import TaskSpec
from ray_tpu._private import tracing

log = get_logger(__name__)


def completion_fields(store, return_ids: list, name: str):
    """(sizes, errs, inline) for one finished task's results — the done
    payload's directory inputs, shared by the task plane and the actor
    host: sizes feed locality scoring, ERRORS cross as pickled
    exceptions (no pullable bytes exist for them), and SMALL RESULTS
    ride inline (<= inline_object_max_bytes — the reference's
    small-return-to-owner path)."""
    sizes: Dict[bytes, int] = {}
    errs: Dict[bytes, bytes] = {}
    inline: Dict[bytes, bytes] = {}
    inline_cap = GlobalConfig.inline_object_max_bytes
    for oid in return_ids:
        ob = oid.binary()
        err = store.peek_error(oid)
        if err is not None:
            try:
                errs[ob] = pickle.dumps(err, protocol=5)
            except Exception:  # noqa: BLE001 — unpicklable error
                from ray_tpu.exceptions import RayTaskError

                errs[ob] = pickle.dumps(
                    RayTaskError(name, repr(err)), protocol=5)
        else:
            size = store.size_of(oid)
            sizes[ob] = size
            # Resident-only: inlining a SPILLED result would pay a
            # synchronous disk restore on the (single) reporter thread,
            # stalling every other completion behind it — spilled bytes
            # move on the pull path instead.
            if size <= inline_cap and store.holds_in_memory(oid):
                try:
                    inline[ob] = store.get(oid, timeout=5.0).to_bytes()
                except Exception:  # noqa: BLE001 — racing eviction
                    pass
    return sizes, errs, inline


def prefetch_serialized(pull_fn: Callable[[bytes], Any], oid_bins: list,
                        pool: ThreadPoolExecutor) -> Dict[bytes, Any]:
    """Pull many objects' serialized bytes CONCURRENTLY (pipelined
    argument prefetch): every pull starts before any finishes, so a
    task's dispatch overlaps its transfers instead of serializing
    behind them. Returns {oid_bin: raw_or_None}; a pull that raised
    maps to its exception (the caller decides per-arg)."""
    futures = {ob: pool.submit(pull_fn, ob) for ob in dict.fromkeys(oid_bins)}
    out: Dict[bytes, Any] = {}
    for ob, fut in futures.items():
        try:
            out[ob] = fut.result()
        except BaseException as exc:  # noqa: BLE001 — per-arg failure
            out[ob] = exc
    return out


class NodeDaemon:
    def __init__(self, address: str, num_cpus: int = 2,
                 resources: Dict[str, float] | None = None,
                 worker_mode: str | None = None):
        import time as _time

        import ray_tpu
        from ray_tpu._private.worker import global_worker

        init_t0 = _time.time()
        ray_tpu.init(num_cpus=num_cpus, resources=resources,
                     worker_mode=worker_mode, address=address)
        self.worker = global_worker()
        self.head = self.worker.head_client
        # Cold-start chain: a node launched FOR a traced request carries
        # RAY_TPU_TRACE_PARENT — its init (runtime boot → registration)
        # becomes a span in that trace, and the join context rides the
        # node_register RPC so the head records its half.
        tracer = tracing.tracer()
        self._join_trace = None
        # The launch context is only meaningful for THIS cold start:
        # once the window passes, drop it from our environment so
        # worker processes spawned for later, unrelated scale-ups
        # don't parent their replica.init into a long-finished trace.
        self._trace_parent_expire = (
            _time.monotonic() + GlobalConfig.trace_cold_start_window_s)
        if tracer is not None:
            tracer.set_identity(component="node",
                                node=self.head.client_id)
            # Spawned worker processes inherit this node identity so
            # their spilled spans carry a cluster-unique process key.
            os.environ[tracing.ENV_NODE] = self.head.client_id
            parent = tracing.cold_start_parent()
            if parent is not None:
                span = tracing.begin("node.init", parent=parent,
                                     component="node")
                span.t0 = init_t0  # covers the runtime boot too
                self._join_trace = tracing.inject(span.ctx)
                self._init_span = span
            else:
                self._init_span = None
        else:
            self._init_span = None
        self.head.handlers["task_push"] = self._on_task_push
        # Direct plane: drivers dial this node's request server and push
        # task batches peer-to-peer (one vectored write per batch); the
        # head relay above stays as the NAT/dial-failure fallback.
        self.head._object_server.handlers["task_push"] = \
            self._on_direct_task_push
        # Drain-before-reap (autoscaler -> head relay -> here, with a
        # direct-plane twin): cordon, finish in-flight work, lease-
        # transfer held result bytes, then report back so the reaper
        # may terminate this process without stranding a borrowed ref.
        self.head.handlers["node_drain"] = self._on_node_drain
        self.head._object_server.handlers["node_drain"] = \
            self._on_node_drain
        # Function-cache pre-ship: a driver that sees this node join
        # pushes its hot function bytes ahead of the first task, so the
        # cold node's first fan-out wave skips the need_fn round trip.
        self.head._object_server.handlers["fn_preship"] = \
            self._on_fn_preship
        # Streaming-generator control plane: consumption acks resume a
        # backpressure-paused producer, cancels stop it between yields.
        # Direct messages from the consuming driver; the pub/sub topic
        # ``stream|<this client>`` is the head-relayed fallback.
        self.head._object_server.handlers["stream_ack"] = self._on_stream_ack
        self.head._object_server.handlers["stream_cancel"] = \
            self._on_stream_cancel
        try:
            self.head.subscribe(f"stream|{self.head.client_id}",
                                self._on_stream_pub)
        except Exception:  # noqa: BLE001 — direct plane still works
            pass
        self.head.status_fn = self._status
        # Cluster actor plane: host actors placed here by remote drivers
        # (direct actor_op requests + head-relayed actor_push fallback).
        from ray_tpu._private.remote_actor import ActorHost

        # Created before ActorHost registers its handlers: an actor op
        # can arrive the moment node_register lands, and its owner
        # callback writes these.
        self._seen_lock = threading.Lock()
        self._last_owner: tuple | None = None  # (addr, driver_id)
        self.actor_host = ActorHost(self.worker, self.head,
                                    on_owner_seen=self._note_owner)
        self.head.node_register(
            self.worker.node_id.hex(), self.worker.resource_pool.total,
            trace=self._join_trace)
        # Head failover: when the client observes a promoted head
        # (epoch bump), re-join — the promoted head replayed membership
        # from the shared log, but a register lost in the dead
        # primary's torn tail (or a log-less head) reconciles here, and
        # the re-join refreshes peer_addr/status ahead of the next
        # heartbeat.
        self.head.failover_callbacks.append(self._on_head_failover)
        if self._init_span is not None:
            tracing.finish(self._init_span)
            self._init_span = None
        # Observability pull plane: peers/state clients dump this node's
        # span ring (+ its worker processes' spilled spans) and its
        # metrics registry — served on the direct object server with a
        # head-relayed twin, zero steady-state cost.
        self.head._object_server.handlers["trace_dump"] = self._on_trace_dump
        self.head.handlers["trace_dump"] = self._on_trace_dump
        self.head._object_server.handlers["metrics_dump"] = \
            self._on_metrics_dump
        self.head.handlers["metrics_dump"] = self._on_metrics_dump
        # Flight-recorder pull plane (same topology): debug_dump ships
        # this node's bundle (+ its worker processes' spilled bundles),
        # flight_ctl toggles the stack sampler live (the bench A/B and
        # operators arm cluster-wide profiling without restarts).
        from ray_tpu._private import flight as _flight

        rec = _flight.recorder()
        if rec is not None:
            rec.set_identity(component="node", node=self.head.client_id)
            os.environ[_flight.ENV_NODE] = self.head.client_id
            rec.add_section("node", self._flight_node_section)
        self.head._object_server.handlers["debug_dump"] = \
            self._on_debug_dump
        self.head.handlers["debug_dump"] = self._on_debug_dump
        self.head._object_server.handlers["flight_ctl"] = \
            self._on_flight_ctl
        self.head.handlers["flight_ctl"] = self._on_flight_ctl
        # Bounded pools replace the old thread-per-pushed-task model:
        # _intake unpacks + prefetches args + submits; _pulls runs the
        # concurrent argument pulls; _reporter ships task_done RPCs
        # (which coalesce into batch frames at the head client).
        self._intake = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="ray_tpu_node_intake")
        self._pulls = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="ray_tpu_node_pull")
        # Wait plane for tasks gated on async-shipped (still-pending)
        # dependencies: wide enough that waiters rarely queue, bounded
        # so a flood cannot spawn unbounded threads. Dep-free tasks
        # (every producer) always flow through _intake, so a consumer
        # here can never starve the producer it waits for.
        self._gated = ThreadPoolExecutor(
            max_workers=64, thread_name_prefix="ray_tpu_node_gated")
        # Recently accepted task ids (exactly-once across ambiguous
        # push-retry windows).
        from collections import deque as _deque

        self._seen_tasks: set = set()
        self._seen_order: "_deque" = _deque()
        # Streaming tasks whose commit listener is already installed
        # (a replayed push must not double-report items).
        self._streaming_wired: set = set()
        # Streaming tasks this node already finished AND cleaned up:
        # late consumption acks for them must not recreate StreamStates
        # (bounded like _seen_tasks).
        self._stream_done: set = set()
        self._stream_done_order: "_deque" = _deque()
        # StreamStates created by an ack that arrived BEFORE any push for
        # the task (the driver's post-accept watermark can race ahead, or
        # the task rerouted to another node after acks were already sent
        # here): bounded LRU so misrouted acks can't grow streams forever.
        self._ack_created_order: "_deque" = _deque()
        # Completion reports coalesce: one reporter thread drains every
        # finish that accumulated while the previous flush was on the
        # wire into ONE announce flight + ONE vectored task_done batch
        # per driver (flush-on-idle — same shape as the push plane).
        from collections import deque

        self._stop = threading.Event()
        self._report_q: "deque" = deque()
        self._report_cv = threading.Condition()
        self._reporter = threading.Thread(
            target=self._report_loop, daemon=True,
            name="ray_tpu_node_done")
        self._reporter.start()
        # Pushed-function cache, keyed by content digest: a fan-out ships
        # the SAME function bytes ONCE per node; every later payload
        # carries only the digest. Byte-capped LRU (pickle size as the
        # weight proxy) so many distinct functions with fat closures
        # can't pin unbounded memory; an evicted digest answers
        # ``need_fn`` and the driver reships.
        from collections import OrderedDict

        self._fn_cache: "OrderedDict[bytes, tuple]" = OrderedDict()
        self._fn_cache_bytes = 0
        self._fn_cache_cap = 64 << 20
        self._fn_lock = threading.Lock()
        self.fn_bytes_received = 0  # bench counter: cache effectiveness
        # Ownership-directory counters: completion batches delivered
        # owner-direct (zero head object traffic) vs. locations the
        # relay fallback had to announce to the head.
        self.direct_report_batches = 0
        self.announce_fallback_oids = 0
        # Drain-before-reap state: once draining, every new push is
        # refused typed ("draining") and the driver reroutes — a node
        # chosen for reap must never accept work it will not report.
        # _result_owner maps node-held result oids -> (driver_id,
        # driver_addr) so drain can lease-transfer each object's bytes
        # back to its owner (bounded FIFO like _seen_tasks; evicted
        # entries fall back to lineage on reap, same as a crash).
        self._draining = False
        self._result_owner: Dict[bytes, tuple] = {}
        self._result_owner_order: "_deque" = _deque()
        self.drain_refusals = 0
        self.drain_transferred = 0
        self.drain_untransferred = 0
        self.fn_preshipped = 0  # functions registered ahead of any push
        # Task-event shipping cursor: the reporter piggybacks this
        # node's ring (events recorded since the last flush) onto its
        # coalesced completion batches — the driver's state API sees
        # cluster tasks with ZERO new steady-state head RPCs.
        self._events_cursor = 0
        self.events_shipped = 0

    def _on_head_failover(self, old_epoch: int, new_epoch: int):
        """Re-join announcement for the promoted head (reconciles the
        replayed membership — idempotent on the head side)."""
        try:
            self.head.node_register(self.worker.node_id.hex(),
                                    self.worker.resource_pool.total)
            log.warning("re-registered with promoted head (epoch %d -> "
                        "%d)", old_epoch, new_epoch)
        except Exception as exc:  # noqa: BLE001 — next failover retries;
            # the log-replayed membership entry still covers us.
            log.warning("node re-register after head failover failed "
                        "(log-replayed membership still covers this "
                        "node): %r", exc)

    def _note_owner(self, addr: tuple, driver_id):
        """Remember the last driver this node reported to (set from
        task completions AND actor ops): tail task events whose
        terminal record landed after the final completion flush ship
        to it on the next heartbeat tick — direct plane, zero head
        RPCs."""
        with self._seen_lock:
            self._last_owner = (addr, driver_id)

    # ------------------------------------------------------ observability
    def _on_trace_dump(self, msg: tuple):
        """This node's span ring + its worker processes' spilled spans,
        optionally filtered to one trace id (hex str, '' = all). A
        truthy third element asks for the per-trace INDEX instead of
        full spans (O(traces) on the wire, the /api/traces listing)."""
        trace_id = None
        if len(msg) > 1 and msg[1]:
            trace_id = msg[1].decode() if isinstance(msg[1], bytes) \
                else str(msg[1])
        t = tracing.tracer()
        if len(msg) > 2 and msg[2]:
            return t.trace_index() if t is not None else {}
        return t.dump(trace_id=trace_id) if t is not None else []

    def _on_metrics_dump(self, msg: tuple):
        """This process's metrics registry in Prometheus text form; the
        scraping side re-labels every sample with node/component tags."""
        from ray_tpu.util.metrics import (
            export_prometheus,
            refresh_framework_metrics,
        )

        refresh_framework_metrics(self.worker)
        return export_prometheus()

    def _on_debug_dump(self, msg: tuple):
        """This node's flight bundle: all-thread stacks, event ring,
        profile aggregate, metrics/chaos snapshots, runtime sections —
        plus the newest spilled bundle from every worker process this
        daemon hosts (they have no dialable server of their own).
        ``{}`` when the recorder is disarmed (the puller skips us)."""
        from ray_tpu._private import flight as _flight
        from ray_tpu.util.metrics import refresh_framework_metrics

        if not _flight.active():
            return {}
        refresh_framework_metrics(self.worker)
        return _flight.local_bundle(include_dir=True) or {}

    def _on_flight_ctl(self, msg: tuple):
        """Live flight-recorder control: ("flight_ctl", "profile", 0|1)
        pauses/resumes this node's stack sampler. Returns a dict (so a
        successful pause — running False — still reads as a truthy
        ANSWER, distinguishable from an unreachable node)."""
        from ray_tpu._private import flight as _flight

        if len(msg) > 2 and msg[1] in ("profile", b"profile"):
            return {"running": bool(_flight.set_profiling(bool(msg[2])))}
        return {"running": False}

    def _flight_node_section(self) -> dict:
        """Node-plane depths for the flight bundle: what this daemon
        was doing (accept/report/drain state) when the dump landed."""
        return {
            "draining": self._draining,
            "drain_refusals": self.drain_refusals,
            "drain_transferred": self.drain_transferred,
            "seen_tasks": len(self._seen_tasks),
            "report_queue": len(self._report_q),
            "fn_cache_bytes": self._fn_cache_bytes,
            "direct_report_batches": self.direct_report_batches,
            "announce_fallback_oids": self.announce_fallback_oids,
            "events_shipped": self.events_shipped,
        }

    # -------------------------------------------------------- function cache
    def _register_fn(self, fn_bytes: bytes) -> bytes:
        """Digest + cache one pushed function's bytes (deserialization is
        deferred to first use). Returns the digest."""
        import hashlib

        key = hashlib.sha256(fn_bytes).digest()
        with self._fn_lock:
            hit = self._fn_cache.get(key)
            if hit is not None:
                self._fn_cache.move_to_end(key)
                return key
            self.fn_bytes_received += len(fn_bytes)
            self._fn_cache[key] = (None, bytes(fn_bytes))
            self._fn_cache_bytes += len(fn_bytes)
            while self._fn_cache_bytes > self._fn_cache_cap \
                    and len(self._fn_cache) > 1:
                _, (_, stale) = self._fn_cache.popitem(last=False)
                self._fn_cache_bytes -= len(stale)
        return key

    def _fn_bytes_for(self, digest: bytes):
        with self._fn_lock:
            hit = self._fn_cache.get(bytes(digest))
            return hit[1] if hit is not None else None

    def _load_fn(self, digest: bytes, fallback_bytes=None):
        """Function for a digest: cache first, else the bytes pinned to
        the task at accept time (an eviction between accept and start
        must not fail a task the node already said 'accepted' to)."""
        import cloudpickle

        key = bytes(digest)
        with self._fn_lock:
            hit = self._fn_cache.get(key)
            if hit is not None:
                self._fn_cache.move_to_end(key)
                fn, fn_bytes = hit
                if fn is not None:
                    return fn
            elif fallback_bytes is None:
                raise KeyError(
                    f"function digest {key.hex()[:16]}… is not cached on "
                    f"this node (evicted between accept and start) and "
                    f"the task carried no pinned bytes")
            else:
                fn_bytes = fallback_bytes
        fn = cloudpickle.loads(fn_bytes)
        with self._fn_lock:
            if key in self._fn_cache:
                self._fn_cache[key] = (fn, fn_bytes)
        return fn

    # ------------------------------------------------------- streaming ctl
    def _on_stream_ack(self, msg: tuple):
        """Consumption watermark from the consuming driver: wakes the
        producer's paused yield loop (thread plane via the stream cv;
        process plane via the pump's ack-channel forwarding). The state
        is CREATED if absent — the post-accept watermark re-send of a
        replayed task can beat _start_task's stream wiring, and a
        dropped ack there would park the replay at the backpressure
        budget forever; _start_task's get_or_create then shares this
        instance. Only acks for already-finished streams are ignored."""
        tid = TaskID(bytes(msg[1]))
        with self._seen_lock:
            done = tid in self._stream_done
        if not done:
            st = self.worker.streams.get(tid)
            if st is None:
                st = self.worker.streams.get_or_create(tid)
                with self._seen_lock:
                    if tid not in self._streaming_wired:
                        # No push for this task has landed here (yet, or
                        # ever — it may have rerouted): keep the orphan
                        # pool bounded. Eviction re-checks wiredness so a
                        # stream the push later claims is never dropped.
                        self._ack_created_order.append(tid)
                        while len(self._ack_created_order) > 4096:
                            old = self._ack_created_order.popleft()
                            if old not in self._streaming_wired:
                                self.worker.streams.pop(old)
            st.advance_consumed(int(msg[2]))
        return None

    def _on_stream_cancel(self, msg: tuple):
        tid = TaskID(bytes(msg[1]))
        st = self.worker.streams.get(tid)
        if st is not None:
            st.cancel()
        self.worker.scheduler.cancel(tid)
        return None

    def _on_stream_pub(self, payload):
        """Head-relayed fallback for stream control messages."""
        try:
            kind = payload[0]
            if kind == "ack":
                self._on_stream_ack((kind, payload[1], payload[2]))
            elif kind == "cancel":
                self._on_stream_cancel((kind, payload[1]))
        except Exception:  # noqa: BLE001 — keep the event thread alive
            pass

    def _status(self) -> dict:
        from ray_tpu.util.metrics import refresh_framework_metrics

        # Heartbeat-rate refresh of the built-in gauges: every node's
        # metrics_dump always carries current series for the cluster
        # scrape to tag.
        refresh_framework_metrics(self.worker)
        if tracing.ENV_PARENT in os.environ \
                and time.monotonic() > self._trace_parent_expire:
            os.environ.pop(tracing.ENV_PARENT, None)
        if self._last_owner is not None and \
                self.worker.task_events.latest_seq() > self._events_cursor:
            # Tail task events with no completion flush to ride (the
            # terminal record can land after the last report went out):
            # nudge the reporter to ship them direct. Owner-gated so a
            # node nobody has reported to yet doesn't wake its reporter
            # every heartbeat for events it cannot ship.
            with self._report_cv:
                self._report_q.append(("events",))
                self._report_cv.notify()
        hosted = sum(1 for a in self.worker.actors.values()
                     if not getattr(a, "borrower", False))
        router = self.worker.remote_router
        return {
            "backlog": self.worker.scheduler.backlog_size(),
            "available": self.worker.resource_pool.available(),
            "actors": hosted,  # borrowed handles are not load
            "unmet": router.unmet_shapes() if router is not None else [],
            # Cordon marker: routers skip draining nodes for NEW
            # placements (the typed push refusal covers the heartbeat
            # staleness window).
            "draining": self._draining,
        }

    # ----------------------------------------------------------- task serve
    def _on_task_push(self, event: tuple):
        return self._accept_payload(event[1])

    def _on_direct_task_push(self, msg: tuple):
        return self._accept_payload(msg[1])

    def _accept_payload(self, payload_bytes):
        """Admission for one pushed task (either plane). The function
        cache is settled synchronously HERE — before the ``accepted``
        reply — so a driver that marks a digest as shipped can never
        race a not-yet-registered cache entry."""
        if self._draining:
            # Reap race: this node was chosen for reap while the push
            # was in flight. Refuse-and-reroute (typed, counted) — an
            # accepted task would execute into a terminating process
            # and its completion report would never land.
            with self._seen_lock:
                self.drain_refusals += 1
            return "draining"
        payload = pickle.loads(bytes(payload_bytes))
        if tracing._TRACER is not None and payload.get("trace") is not None:
            # submit→accept hop: register the context (one extract, one
            # lock) so the scheduler's task-event bridge emits this
            # task's queue/exec spans, and stamp the arrival.
            ctx = tracing.extract(payload["trace"])
            if ctx is not None:
                tracing.register_task(bytes(payload["task_id"]), ctx)
                tracing.event("task.accept", ctx=ctx, component="node",
                              task=payload.get("name", ""))
        fn_bytes = payload.get("fn")
        digest = payload.get("fn_digest")
        if fn_bytes:
            digest = payload["fn_digest"] = self._register_fn(fn_bytes)
            payload["fn"] = None  # cached; drop the heavy reference
        else:
            # ONE locked lookup settles presence AND pins the bytes to
            # this task — a concurrent eviction between a separate
            # membership check and the pin would fail a task the node
            # already answered "accepted" for.
            fn_bytes = self._fn_bytes_for(digest) if digest else None
            if fn_bytes is None:
                return "need_fn"  # evicted/restarted: driver reships
        # Pinned: an LRU eviction between accept and start cannot fail
        # the task (the bytes ride the queued payload).
        payload["_fn_bytes"] = fn_bytes
        # Exactly-once across the ambiguous-failure window: a direct
        # push whose connection died after the send may be resent
        # verbatim via the head relay — the task already runs here, so
        # a repeated (task_id, push_id) is acknowledged without
        # re-submitting (side effects must not double). Deliberate
        # re-pushes (lineage re-execution, need_fn reships) carry a
        # FRESH push_id and are admitted; need_fn refusals never enter
        # this set.
        key = bytes(payload["task_id"]) + bytes(
            payload.get("push_id") or b"")
        with self._seen_lock:
            if key in self._seen_tasks:
                return "accepted"
            self._seen_tasks.add(key)
            self._seen_order.append(key)
            while len(self._seen_order) > 65536:
                self._seen_tasks.discard(self._seen_order.popleft())
        # Tasks whose PENDING pull-refs (producer still in flight when
        # the driver shipped them) are not yet local may WAIT here up to
        # the dep-wait bound — they run on a separate bounded wait plane
        # so gated waiters can never clog the intake/pull pools or
        # deadlock a producer queued behind its consumers (producers
        # with no pending deps always flow through _intake).
        pending = any(
            not self.worker.store.is_ready(ObjectID(bytes(ob)))
            for ob in payload.get("pending_refs") or ())
        if pending:
            payload["_gated"] = True
            self._gated.submit(self._start_task, payload)
        else:
            self._intake.submit(self._start_task, payload)
        return "accepted"

    def _ensure_object(self, oid_bin: bytes,
                       deadline: float | None = None,
                       owner: tuple | None = None):
        """Materialize one pull-ref's bytes into the local store through
        its OWNER (the driver that pushed the task): ``owner_locate``
        over the p2p plane names the node holding the bytes — or
        subscribes this node when the producer is still in flight (async
        dependency shipping), so the owner's ``owner_notify`` wakes the
        wait the moment the completion report lands. The head's
        directory is strictly the fallback (owner unreachable /
        lease-transferred entries); a dead owner with no fallback copy
        materializes a typed ``OwnerDiedError``. A producer that FAILED
        arrives as a pickled error in the locate answer; it
        materializes locally so execution reports the real error
        instead of a timeout."""
        oid = ObjectID(bytes(oid_bin))
        store = self.worker.store
        if store.is_ready(oid):
            return
        if deadline is None:
            deadline = time.monotonic() + GlobalConfig.dep_wait_s
        owner_id = owner[0] if owner else None
        owner_addr = tuple(owner[1]) if owner and owner[1] else None
        self.worker.owner_resolver.resolve(
            oid.binary(), owner_addr, owner_id, deadline=deadline,
            stop=self._stop)

    def _unwire_arg(self, wired: tuple, deadline: float | None = None,
                    owner: tuple | None = None):
        from ray_tpu._private.serialization import SerializedObject

        kind, data = wired
        if kind == "v":
            return self.worker.serialization_context.deserialize(
                SerializedObject.from_bytes(data))
        # Pull-ref: prefetched into the store by _start_task.
        oid = ObjectID(bytes(data))
        self._ensure_object(oid.binary(), deadline, owner)  # no-op when
        serialized = self.worker.store.get(oid)              # prefetched
        return self.worker.serialization_context.deserialize(serialized)

    def _start_task(self, payload: dict):
        """Unpack a pushed task, prefetch its remote args in parallel
        (waiting out pending producers — the execution gate for async-
        shipped dependencies), submit to the local scheduler, and report
        completion from the store's ready callbacks — no blocking wait,
        no per-task thread (event-driven dispatch end to end)."""
        return_ids = [ObjectID(bytes(b)) for b in payload["return_ids"]]
        # This node will produce these objects: gated waiters for them
        # (colocated consumers) ride the store's ready event instead of
        # polling the head's directory.
        for oid in return_ids:
            self.worker.store.mark_local_producer(oid)
        streaming = bool(payload.get("streaming"))
        if streaming:
            # Pre-wire the producer-side stream BEFORE execution: every
            # yield's commit enqueues an item_done report (small items
            # inline, large items announce + p2p pull — the per-yield
            # analogue of task_done). A replayed push reuses the existing
            # state, so the listener installs exactly once per task.
            tid = TaskID(bytes(payload["task_id"]))
            with self._seen_lock:
                fresh = tid not in self._streaming_wired
                self._streaming_wired.add(tid)
                # A deliberate re-push (lineage recovery on the same
                # node) reopens the stream: acks must apply again.
                self._stream_done.discard(tid)
            if fresh:
                stream = self.worker.streams.get_or_create(tid)

                def _on_commit(idx, oid, _payload=payload):
                    with self._report_cv:
                        self._report_q.append(("item", _payload, idx, oid))
                        self._report_cv.notify()

                stream.add_commit_listener(_on_commit)
        try:
            fn = self._load_fn(payload["fn_digest"],
                               payload.get("_fn_bytes"))
            deadline = time.monotonic() + GlobalConfig.dep_wait_s
            # The pushing driver OWNS every pull-ref in this payload
            # (its router inlines foreign-owned values before shipping):
            # resolve arg locations owner-direct, not through the head.
            # Owner tuples are (owner_id, addr) everywhere — the same
            # order serialized refs carry.
            owner = (payload.get("driver_id"), payload.get("driver_addr"))
            wired = list(payload["args"]) + list(payload["kwargs"].values())
            pull_bins = [bytes(d) for k, d in wired if k == "r"]
            dep_span = None
            if pull_bins and tracing._TRACER is not None \
                    and payload.get("trace") is not None:
                dep_span = tracing.begin(
                    "task.dep_fetch",
                    parent=tracing.extract(payload["trace"]),
                    component="node", task=payload.get("name", ""),
                    num_deps=len(pull_bins))
            try:
                if payload.get("_gated"):
                    # Pending producers: this task runs on its OWN
                    # thread, so wait-out pulls happen inline — the
                    # shared pull pool stays free for immediately-
                    # resolvable transfers.
                    for ob in pull_bins:
                        self._ensure_object(ob, deadline, owner)
                elif pull_bins:
                    # Pool threads have no ambient thread-local trace
                    # context: re-enter the dep-fetch span's so their
                    # pull meta frames carry it (no-op when off).
                    dep_ctx = dep_span.ctx if dep_span is not None \
                        else None

                    def _pull(ob, _ctx=dep_ctx):
                        with tracing.use_context(_ctx):
                            return self._ensure_object(ob, deadline,
                                                       owner)

                    prefetched = prefetch_serialized(
                        _pull, pull_bins, self._pulls)
                    for exc in prefetched.values():
                        if isinstance(exc, BaseException):
                            raise exc
            except BaseException:
                tracing.finish(dep_span, status="error")
                dep_span = None
                raise
            finally:
                tracing.finish(dep_span)
            args = tuple(self._unwire_arg(a, deadline, owner)
                         for a in payload["args"])
            kwargs = {k: self._unwire_arg(v, deadline, owner)
                      for k, v in payload["kwargs"].items()}
            spec = TaskSpec(
                task_id=TaskID(bytes(payload["task_id"])),
                function=fn, args=args, kwargs=kwargs,
                num_returns=payload["num_returns"],
                return_ids=return_ids,
                name=payload["name"],
                resources=dict(payload["resources"]),
                max_retries=payload["max_retries"],
                retry_exceptions=payload["retry_exceptions"],
                runtime_env=payload.get("runtime_env"),
                streaming=streaming,
                backpressure=int(payload.get("backpressure", 0)),
                trace=payload.get("trace"))
            self.worker.scheduler.submit(spec)
        except BaseException as exc:  # noqa: BLE001 — report, don't die
            from ray_tpu.exceptions import RayTaskError

            err = exc if isinstance(exc, RayTaskError) else \
                RayTaskError.from_exception(payload.get("name", "task"), exc)
            for oid in return_ids:
                if not self.worker.store.is_ready(oid):
                    self.worker.store.put_error(oid, err)
        # Completion rides the store's ready callbacks (errors also
        # materialize as ready): when the LAST output lands, report
        # task_done from the reporter pool — the RPC itself coalesces
        # into the head client's batch frames.
        remaining = [len(return_ids)]
        lock = threading.Lock()

        def _one_ready():
            with lock:
                remaining[0] -= 1
                if remaining[0] != 0:
                    return
            with self._report_cv:
                self._report_q.append(("done", payload, return_ids))
                self._report_cv.notify()

        for oid in return_ids:
            self.worker.store.on_ready(oid, _one_ready)

    def _build_done(self, payload: dict, return_ids: list):
        """(done_bytes, oid_bins, driver_addr, driver_id) for one
        finished task (completion_fields carries the shared
        sizes/errs/inline semantics). ERRORED oids are announced too:
        a remote consumer's pull then RAISES the typed task error (the
        owner's store serves errors by raising; wire_to_exc keeps the
        type) instead of spinning against a location-less directory
        until the dep-wait bound."""
        sizes, errs, inline = completion_fields(
            self.worker.store, return_ids, payload.get("name", "task"))
        oid_bins = [o.binary() for o in return_ids]
        # Drain bookkeeping: results whose BYTES stay node-held (too
        # big to inline) are exactly the refs a reap could strand —
        # remember their owner so drain can offload them back.
        addr0 = payload.get("driver_addr")
        if addr0:
            with self._seen_lock:
                for ob in sizes:
                    if ob not in inline and ob not in self._result_owner:
                        self._result_owner[ob] = (
                            payload["driver_id"], tuple(addr0))
                        self._result_owner_order.append(ob)
                while len(self._result_owner_order) > 65536:
                    self._result_owner.pop(
                        self._result_owner_order.popleft(), None)
        done_fields = {
            "task_id": bytes(payload["task_id"]),
            "oid_bins": oid_bins,
            "node_client": self.head.client_id,
            "sizes": sizes,
            "errs": errs,
            "inline": inline,
        }
        # Ship this node's task-event ring home piggybacked on the
        # completion report (exactly the coalesced batch that is going
        # out anyway — no new RPC, no new frame): the driver ingests
        # them so util.state.list_tasks() covers cluster tasks.
        events = self._drain_reportable_events()
        if events:
            done_fields["node_events"] = events
            self.events_shipped += len(events)
        done = pickle.dumps(done_fields, protocol=5)
        addr = payload.get("driver_addr")
        if addr:
            self._note_owner(tuple(addr), payload["driver_id"])
        return (done, oid_bins, tuple(addr) if addr else None,
                payload["driver_id"])

    def _build_item(self, payload: dict, idx: int, oid):
        """One yield's item_done report: inline the bytes when small
        (<= inline_object_max_bytes), else ship owner + size so the
        consumer pulls p2p. Returns (item_bytes, announce_oid_or_None,
        addr, driver_id)."""
        store = self.worker.store
        size = store.size_of(oid)
        inline = None
        if size <= GlobalConfig.inline_object_max_bytes \
                and store.holds_in_memory(oid):
            try:
                inline = store.get(oid, timeout=5.0).to_bytes()
            except Exception:  # noqa: BLE001 — racing eviction
                pass
        item_fields = {
            "task_id": bytes(payload["task_id"]),
            "idx": int(idx),
            "oid": oid.binary(),
            "inline": inline,
            "size": size,
            "node_client": self.head.client_id,
        }
        if tracing._TRACER is not None and payload.get("trace") is not None:
            # Streaming per-yield reports carry the producer task's
            # context: the consumer stamps stream.item trace events.
            item_fields["trace"] = payload["trace"]
        item = pickle.dumps(item_fields, protocol=5)
        addr = payload.get("driver_addr")
        announce = oid.binary() if inline is None else None
        if announce is not None and addr:
            # Streamed items too big to inline are node-held borrowed
            # bytes exactly like task returns: drain must be able to
            # lease-transfer them, or reaping an idle producer node
            # strands the consumer's not-yet-pulled tail items.
            with self._seen_lock:
                if announce not in self._result_owner:
                    self._result_owner[announce] = (
                        payload["driver_id"], tuple(addr))
                    self._result_owner_order.append(announce)
                while len(self._result_owner_order) > 65536:
                    self._result_owner.pop(
                        self._result_owner_order.popleft(), None)
        return (item, announce, tuple(addr) if addr else None,
                payload["driver_id"])

    def _report_loop(self):
        """Drain finished tasks into batched completion reports: ONE
        vectored task_done/item_done batch pushed DIRECT to each
        driver's object server. Under the ownership directory the
        driver that pushed the task OWNS its results — the direct
        report IS the location record (the owner's table answers peer
        ``owner_locate`` queries), so the head sees ZERO steady-state
        object traffic. Only the per-driver RELAY fallback (NAT'd
        drivers, dial failure) still announces its batch's locations to
        the head first — the relayed consumer resolves through the
        head's fallback directory. Streaming item_done reports ride the
        same batches: many yields that accumulate while one flush is on
        the wire coalesce into one vectored flight per driver.
        ``ownership_directory=false`` restores the pre-ownership
        announce-everything behavior."""
        from ray_tpu._private.object_server import PeerUnreachableError

        while True:
            with self._report_cv:
                while not self._report_q and not self._stop.is_set():
                    self._report_cv.wait()
                if self._stop.is_set() and not self._report_q:
                    return
                items = list(self._report_q)
                self._report_q.clear()
            # ("task_done"/"item_done", bytes, addr, drv, announce_oids)
            built = []
            tail_events = False
            for entry in items:
                try:
                    if entry[0] == "events":
                        tail_events = True
                        continue
                    if entry[0] == "item":
                        _, payload, idx, oid = entry
                        item, ann, addr, drv = self._build_item(
                            payload, idx, oid)
                        built.append(("item_done", item, addr, drv,
                                      [ann] if ann is not None else []))
                    else:
                        _, payload, return_ids = entry
                        done, oid_bins, addr, drv = self._build_done(
                            payload, return_ids)
                        built.append(("task_done", done, addr, drv,
                                      oid_bins))
                        if payload.get("streaming"):
                            tid = TaskID(bytes(payload["task_id"]))
                            self.worker.streams.pop(tid)
                            with self._seen_lock:
                                self._streaming_wired.discard(tid)
                                self._stream_done.add(tid)
                                self._stream_done_order.append(tid)
                                while len(self._stream_done_order) > 65536:
                                    self._stream_done.discard(
                                        self._stream_done_order.popleft())
                except Exception as exc:  # keep reporting others
                    log.warning("dropping one malformed completion "
                                "record; reporting the rest: %r", exc)
            ownership = GlobalConfig.ownership_directory
            announced = True
            if not ownership:
                # Centralized directory (rollback lever): every result
                # location coalesces through the head BEFORE completion
                # reports go out — direct completion is only legal once
                # the directory can serve later cross-node pulls.
                announce = [ob for rec in built for ob in rec[4]]
                try:
                    self.head.object_announce_many(announce)
                except Exception as exc:  # head hiccup: take the relay,
                    announced = False     # which re-records locations
                    log.debug("announce batch failed; falling back to "
                              "relayed completions: %r", exc)
            by_driver: Dict[tuple, list] = {}
            for rec in built:
                by_driver.setdefault((rec[2], rec[3]), []).append(rec)
            for (addr, driver_id), entries in by_driver.items():
                if addr is not None and announced:
                    try:
                        replies = self.head._peers.call_many(
                            addr, [(kind, data)
                                   for kind, data, *_ in entries])
                        # call_many surfaces DRIVER-side handler errors
                        # as exception objects per message: those
                        # records were NOT delivered — they must take
                        # the relay below or their completion (and only
                        # location record) is silently lost.
                        failed = [rec for rec, rep in zip(entries,
                                                          replies)
                                  if isinstance(rep, BaseException)]
                        if not failed:
                            self.direct_report_batches += 1
                            continue
                        log.warning("%d completion record(s) failed in "
                                    "the driver's handler; relaying "
                                    "them via the head", len(failed))
                        entries = failed
                    except PeerUnreachableError:
                        pass  # driver not directly dialable: relay below
                if ownership:
                    # Relay fallback under ownership: the head becomes
                    # the directory of record for THIS batch. The
                    # task_done relay records its oid locations
                    # server-side; only large streamed items (announce +
                    # pull) need the explicit announce flight.
                    fallback = [ob for rec in entries for ob in rec[4]
                                if rec[0] == "item_done"]
                    try:
                        if fallback:
                            self.head.object_announce_many(fallback)
                        self.announce_fallback_oids += len(fallback)
                    except Exception as exc:  # pub/sub item consumers
                        log.debug("fallback announce failed (item pulls "
                                  "resolve via owner only): %r", exc)
                dones = [(rec[4], rec[1]) for rec in entries
                         if rec[0] == "task_done"]
                try:
                    if dones:
                        # One coalesced flight for the whole batch — the
                        # relay fallback must not serialize N round trips.
                        self.head.task_done_many(driver_id, dones)
                    for rec in entries:
                        if rec[0] == "item_done":
                            # Per-item relay fallback rides pub/sub.
                            self.head.publish(f"stream|{driver_id}",
                                              ("item_done", rec[1]))
                except Exception as exc:  # driver gone: results stay
                    log.debug("completion relay to driver %s failed "
                              "(results stay local): %r", driver_id, exc)
            if tail_events:
                # Completion batches in this drain already shipped what
                # they could; anything recorded since goes direct to
                # the last reported-to driver (best-effort telemetry —
                # still zero head RPCs).
                self._flush_tail_events()

    def _drain_reportable_events(self):
        """Drain task events past the shipping cursor, rendered to the
        wire tuple shape both shipping paths (piggybacked ``node_events``
        and the direct ``task_events`` tail flush) unpack. Only the
        states the cluster view renders ship (RUNNING + terminal);
        transient PENDING_* bookkeeping stays local. Reporter-thread
        only: the cursor advances unconditionally."""
        cursor, fresh = self.worker.task_events.drain_since(
            self._events_cursor)
        self._events_cursor = cursor
        return [(ev.task_id.binary(), ev.state, ev.timestamp, ev.name,
                 ev.duration) for ev in fresh
                if not ev.state.startswith("PENDING")]

    def _flush_tail_events(self):
        with self._seen_lock:
            owner = self._last_owner
        if owner is None or owner[0] is None:
            return
        events = self._drain_reportable_events()
        if not events:
            return
        blob = pickle.dumps((self.head.client_id, events), protocol=5)
        try:
            self.head._peers.call(tuple(owner[0]),
                                  ("task_events", blob))
            self.events_shipped += len(events)
        except Exception as exc:  # noqa: BLE001 — owner gone: telemetry
            log.debug("tail task-event ship to %s failed (telemetry "
                      "only): %r", owner[1], exc)

    # ----------------------------------------------------------------- drain
    def _on_fn_preship(self, msg: tuple):
        """Function-cache pre-ship on node join: register pushed
        function bytes ahead of any task so a cold node's first wave
        skips the need_fn round trip. Idempotent (digest-keyed)."""
        count = 0
        for fnb in msg[1]:
            self._register_fn(bytes(fnb))
            count += 1
        with self._seen_lock:
            self.fn_preshipped += count
        return count

    def _on_node_drain(self, msg: tuple):
        """Drain-before-reap: cordon this node (new pushes refuse
        typed), wait out in-flight tasks and pending completion
        reports, then lease-transfer node-held result bytes to their
        owning drivers (``object_offload`` over the direct plane) and
        re-point the head's fallback directory entries at the new
        holder (``object_transfer`` — the PR 10 lease-handoff path).
        Returns the drain report; the reaper terminates the process
        only after this reply, so a drained reap can never strand a
        borrowed ref. Bounded by the caller-supplied timeout — a
        wedged drain degrades to crash semantics (lineage replay).

        Exactly-once under racing reapers: the FIRST drain claims the
        node (cordon); a concurrent second pass observes the cordon
        and returns immediately with ``already_draining`` set and
        current counters — it must neither re-run the offload (double
        ``object_offload`` would double-count lease transfers) nor be
        treated by its caller as a completed drain it owns."""
        timeout_s = float(msg[1]) if len(msg) > 1 else 15.0
        with self._seen_lock:
            if self._draining:
                return {"transferred": self.drain_transferred,
                        "untransferred": self.drain_untransferred,
                        "refused": self.drain_refusals,
                        "already_draining": True}
            self._draining = True
        deadline = time.monotonic() + max(timeout_s, 0.1)
        # 1. In-flight work finishes: queued + running tasks, then the
        # reporter queue flushes (a completed task whose report never
        # left would strand its locations driver-side as "pending").
        while time.monotonic() < deadline:
            with self._report_cv:
                reports_pending = bool(self._report_q)
            if self.worker.scheduler.backlog_size() == 0 \
                    and not reports_pending:
                break
            time.sleep(0.05)
        # 2. Lease-transfer node-held result bytes, grouped per owner.
        with self._seen_lock:
            owned = list(self._result_owner.items())
        by_owner: Dict[tuple, list] = {}
        store = self.worker.store
        for ob, owner in owned:
            oid = ObjectID(bytes(ob))
            if not store.is_ready(oid) or store.peek_error(oid) \
                    is not None:
                continue
            try:
                raw = store.get(oid, timeout=5.0).to_bytes()
            except Exception:  # noqa: BLE001 — racing eviction
                continue
            by_owner.setdefault(owner, []).append((ob, raw))
        transferred: list = []  # (oid_bin, holder) for the head re-point
        for (drv, addr), entries in by_owner.items():
            # Chunked flights bound the frame size; the driver stores
            # the bytes locally and re-points its owner table.
            # Accounting is PER CHUNK: a partially-successful owner
            # transfer counts exactly what moved (transferred +
            # untransferred always sums to the held set).
            for i in range(0, len(entries), 64):
                chunk = entries[i:i + 64]
                try:
                    self.head._peers.call(
                        tuple(addr), ("object_offload", chunk))
                    transferred.extend((ob, drv) for ob, _ in chunk)
                    self.drain_transferred += len(chunk)
                except Exception as exc:  # noqa: BLE001 — owner gone:
                    self.drain_untransferred += len(chunk)
                    log.warning("drain offload of %d object(s) to "
                                "driver %s failed (lineage will "
                                "replay): %r", len(chunk), drv, exc)
        # 3. Re-point head FALLBACK directory entries naming this node
        # as holder: the owning driver holds the bytes now, so relayed
        # borrowers keep resolving after this process exits.
        if transferred:
            try:
                self.head.object_transfer_many(transferred)
            except Exception as exc:  # noqa: BLE001 — head gone: the
                log.debug("drain head re-point failed (owner-direct "
                          "resolution still covers these): %r", exc)
        return {"transferred": self.drain_transferred,
                "untransferred": self.drain_untransferred,
                "refused": self.drain_refusals,
                "already_draining": False}

    # -------------------------------------------------------------- lifecycle
    def run_forever(self):
        signal.signal(signal.SIGTERM, lambda *_: self._stop.set())
        try:
            while not self._stop.wait(0.5):
                pass
        except KeyboardInterrupt:
            pass
        self.shutdown()

    def shutdown(self):
        import ray_tpu

        self._stop.set()
        with self._report_cv:
            self._report_cv.notify_all()
        for pool in (self._intake, self._pulls, self._gated):
            pool.shutdown(wait=False, cancel_futures=True)
        self.actor_host.shutdown()
        ray_tpu.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--address", required=True, help="head host:port")
    ap.add_argument("--num-cpus", type=int, default=2)
    ap.add_argument("--resources", default="{}",
                    help='extra resources, e.g. \'{"accel": 1}\'')
    ap.add_argument("--worker-mode", default=None,
                    choices=(None, "process", "thread"))
    args = ap.parse_args(argv)
    daemon = NodeDaemon(
        args.address, num_cpus=args.num_cpus,
        resources=json.loads(args.resources),
        worker_mode=args.worker_mode)
    print(f"ray_tpu node {daemon.worker.node_id.hex()[:16]} joined "
          f"{args.address} as {daemon.head.client_id}", flush=True)
    daemon.run_forever()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
