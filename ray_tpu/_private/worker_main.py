"""Worker process entrypoint: the leased-worker execution loop.

Rebuild of the reference's worker process main (reference role:
python/ray/_private/workers/default_worker.py + the CoreWorker task
execution loop it enters [unverified]). The driver's WorkerPool spawns this
module as a subprocess per worker; requests arrive over a shared-memory
mutable-object channel (the plasma-mutable-object analogue), argument and
result payloads ride the shared-memory object store, and replies go back on
a second channel. A ``kill -9`` of this process is detected by the driver
through process liveness + reply timeout and surfaces as
``WorkerCrashedError`` — never as a driver crash.
"""

from __future__ import annotations

import argparse
import os
import pickle
import sys
import traceback
from typing import Any, Dict, List, Optional

from ray_tpu._private import tracing


class _ShmRef:
    """Marker for an argument stored in the shm object store."""

    __slots__ = ("key",)

    def __init__(self, key: int):
        self.key = key


def _fetch_blob(store, field):
    """Inverse of worker_pool.maybe_stage: ('shm', key) markers resolve
    through the store (the driver deletes the key after the reply)."""
    if isinstance(field, tuple) and len(field) == 2 and field[0] == "shm":
        return bytes(store.get(field[1]))
    return field


def _load_payload(store, ctx, payload: bytes):
    """Deserialize (args, kwargs), fetching _ShmRef args from the store."""
    from ray_tpu._private.serialization import SerializedObject

    args, kwargs = pickle.loads(payload)

    def _fetch(v):
        if isinstance(v, _ShmRef):
            raw = bytes(store.get(v.key))
            return ctx.deserialize(SerializedObject.from_bytes(raw))
        return v

    return (tuple(_fetch(a) for a in args),
            {k: _fetch(v) for k, v in kwargs.items()})


def _store_outputs(store, ctx, return_keys: List[int], result: Any,
                   num_returns: int):
    if num_returns <= 1:
        outputs = [result]
    else:
        outputs = list(result)
        if len(outputs) != num_returns:
            raise ValueError(
                f"task declared num_returns={num_returns} but returned "
                f"{len(outputs)} values")
    for key, value in zip(return_keys, outputs):
        store.put(key, ctx.serialize(value).to_bytes())


def _run_dag_stages(store, desc: dict, actor_instance) -> None:
    """Worker-resident compiled-DAG exec loop over shm channels.

    Never raises: any failure is logged to stderr (the driver's log
    plane) and terminates the loop — the caller must reply exactly once
    on the request channel, so a stray exception here must not reach the
    main loop's error boundary (double reply = protocol desync).
    """
    from ray_tpu.channels.channel import ShmBufferedChannel
    from ray_tpu.dag.compiled_dag import _Stage
    from ray_tpu.exceptions import ChannelError, ChannelTimeoutError

    try:
        chans = {cid: ShmBufferedChannel.attach(store, spec)
                 for cid, spec in desc["channels"].items()}
        stages = []
        for sd in desc["stages"]:
            sources = []
            for kind, a, b in sd["arg_sources"]:
                if kind == "const":
                    sources.append(("const", pickle.loads(a), None))
                else:
                    sources.append(("chan", chans[a], b))
            stages.append(_Stage(
                node=None, fn=None, arg_sources=sources,
                out_channel=chans[sd["out_channel"]],
                method_name=sd["method_name"]))
        while True:
            try:
                for stage in stages:
                    stage.run_once(actor_instance)
            except ChannelTimeoutError:
                if os.getppid() == 1:
                    return  # orphaned: the driver died without teardown
                continue  # producer/consumer slow: retry
            except ChannelError:
                return  # teardown closed the channels
    except BaseException:  # noqa: BLE001 — log, never propagate
        print("ray_tpu compiled-DAG worker loop failed:\n"
              + traceback.format_exc(), file=sys.stderr, flush=True)


def _run_stream_yields(gen, ctx, max_msg: int, stage_result, emit,
                       budget: int, wait_acks):
    """Producer yield loop shared by every process-plane stream flavor
    (task_stream, actor_stream, mux actor items): serialize each yield,
    ``emit`` it (small items inline in the frame, big items staged in the
    shm store), then run the pause protocol — ``wait_acks(count)`` blocks
    while committed-but-unconsumed items have reached ``budget`` and
    returns False when the consumer cancelled. Returns
    ``(total, cancelled)``."""
    limit = max(max_msg // 4, 64 * 1024)
    if not hasattr(gen, "__iter__") and not hasattr(gen, "__next__"):
        raise TypeError(
            f"streaming task returned non-iterable {type(gen).__name__}")
    it = iter(gen)
    idx = 0
    try:
        for item in it:
            raw = ctx.serialize(item).to_bytes()
            field = ("shm", stage_result(raw)) if len(raw) > limit else raw
            emit(idx, field)
            idx += 1
            if not wait_acks(idx):
                return idx, True
    except BaseException:
        close = getattr(it, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001 — generator cleanup
                pass
        raise
    return idx, False


def worker_loop(store_name: str, req_id: int, rep_id: int,
                worker_id: int, max_msg: int,
                api_req_id: int = 0, api_rep_id: int = 0,
                ack_id: int = 0) -> None:
    # Workers never touch the TPU: the device belongs to the driver (the
    # compiled-graph path); keep jax (if imported by user code) on CPU.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import cloudpickle

    from ray_tpu._native.store import NativeMutableChannel, NativeObjectStore
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.ids import TaskID
    from ray_tpu._private.serialization import SerializationContext
    from ray_tpu.exceptions import ChannelError, ChannelTimeoutError, \
        RayTaskError

    store = NativeObjectStore.open(store_name)
    req = NativeMutableChannel(store, req_id, max_size=max_msg,
                               num_readers=1, create=False)
    rep = NativeMutableChannel(store, rep_id, max_size=max_msg,
                               num_readers=1, create=False)
    ack = None
    if ack_id:
        # Streaming backpressure acks (driver -> this worker); read only
        # inside a stream's pause/poll points, so it never interleaves
        # with the request protocol.
        ack = NativeMutableChannel(store, ack_id, max_size=8192,
                                   num_readers=1, create=False)

    # Install the client-mode runtime so ray_tpu.* API calls made inside
    # task/actor code forward to the driver instead of booting a second
    # full runtime in this process.
    if api_req_id and api_rep_id:
        from ray_tpu._private.client_worker import ClientWorker

        api_req = NativeMutableChannel(store, api_req_id, max_size=max_msg,
                                       num_readers=1, create=False)
        api_rep = NativeMutableChannel(store, api_rep_id, max_size=max_msg,
                                       num_readers=1, create=False)
        worker_mod._global_worker = ClientWorker(
            store, api_req, api_rep, worker_id)

    ctx = SerializationContext()
    fn_cache: Dict[bytes, Any] = {}
    actor_instance: Optional[Any] = None
    actor_state: Dict[str, Any] = {}  # concurrency plane for actor_new2
    import threading as _threading_mod

    rep_lock = _threading_mod.Lock()
    _stage_counter = [0]
    _stage_lock = _threading_mod.Lock()  # concurrent actor calls stage too

    def _reply(msg):
        with rep_lock:
            rep.write(msg)

    def _stage_result(raw: bytes) -> int:
        with _stage_lock:
            _stage_counter[0] += 1
            n = _stage_counter[0]
        key = (0xA4D0_0000_0000_0000
               | (os.getpid() & 0xFFFFFF) << 24
               | (n & 0xFF_FFFF))
        store.put(key, raw)
        return key

    def _finish_actor_call(call_id, result, return_keys, num_returns):
        if return_keys:
            _store_outputs(store, ctx, return_keys, result, num_returns)
            _reply(("calldone", call_id, "ok", None))
        else:
            raw = ctx.serialize(result).to_bytes()
            if len(raw) > max(max_msg // 4, 64 * 1024):
                _reply(("calldone", call_id, "okshm", _stage_result(raw)))
            else:
                _reply(("calldone", call_id, "okv", raw))

    def _fail_actor_call(call_id, name, exc):
        try:
            err = RayTaskError.from_exception(str(name), exc)
            _reply(("calldone", call_id, "err", pickle.dumps(err)))
        except Exception:  # noqa: BLE001 — unpicklable cause fallback
            err = RayTaskError(str(name), traceback.format_exc(), cause=None)
            _reply(("calldone", call_id, "err", pickle.dumps(err)))

    def _stream_actor_result(call_id, result, task_id_bin, budget,
                             wait_acks):
        """Emit one actor call's generator result as mux item frames."""
        total, cancelled = _run_stream_yields(
            result, ctx, max_msg, _stage_result,
            lambda i, f: _reply(("calldone", call_id, "item", (i, f))),
            budget, wait_acks)
        _reply(("calldone", call_id,
                "cancelled" if cancelled else "ok_stream", total))

    def _run_actor_call_sync(call_id, method_name, payload, return_keys,
                             num_returns, task_id_bin, name,
                             stream_budget=None):
        try:
            method = getattr(actor_instance, method_name)
            args, kwargs = _load_payload(store, ctx,
                                         _fetch_blob(store, payload))
            _set_task_ctx(task_id_bin, name)
            try:
                result = method(*args, **kwargs)
                if stream_budget is not None:
                    _stream_actor_result(
                        call_id, result, task_id_bin, stream_budget,
                        _mux_ack_waiter(task_id_bin, stream_budget))
                    return
            finally:
                _set_task_ctx(None, None)
                if stream_budget is not None:
                    _mux_stream_done(task_id_bin)
            _finish_actor_call(call_id, result, return_keys, num_returns)
        except BaseException as exc:  # noqa: BLE001 — call error boundary
            _fail_actor_call(call_id, name, exc)

    async def _run_actor_call_async(call_id, method_name, payload,
                                    return_keys, num_returns, task_id_bin,
                                    name, stream_budget=None):
        import inspect as _inspect

        try:
            method = getattr(actor_instance, method_name)
            args, kwargs = _load_payload(store, ctx,
                                         _fetch_blob(store, payload))
            _set_task_ctx(task_id_bin, name)
            try:
                result = method(*args, **kwargs)
                if _inspect.iscoroutine(result):
                    result = await result
                if stream_budget is not None:
                    if hasattr(result, "__anext__"):
                        await _stream_actor_result_async(
                            call_id, result, task_id_bin, stream_budget)
                    else:
                        # Sync generator from an async actor: iterate on
                        # the executor so the event loop stays live.
                        import asyncio as _asyncio

                        await _asyncio.get_running_loop().run_in_executor(
                            None, _stream_actor_result, call_id, result,
                            task_id_bin, stream_budget,
                            _mux_ack_waiter(task_id_bin, stream_budget))
                    return
            finally:
                _set_task_ctx(None, None)
                if stream_budget is not None:
                    _mux_stream_done(task_id_bin)
            _finish_actor_call(call_id, result, return_keys, num_returns)
        except BaseException as exc:  # noqa: BLE001 — call error boundary
            _fail_actor_call(call_id, name, exc)

    async def _stream_actor_result_async(call_id, agen, task_id_bin,
                                         budget):
        """Async-generator flavor of the mux item stream (pause points
        poll the ack table without blocking the event loop)."""
        import asyncio as _asyncio

        limit = max(max_msg // 4, 64 * 1024)
        key = bytes(task_id_bin)
        idx = 0
        cancelled = False
        async for item in agen:
            raw = ctx.serialize(item).to_bytes()
            field = ("shm", _stage_result(raw)) if len(raw) > limit \
                else raw
            _reply(("calldone", call_id, "item", (idx, field)))
            idx += 1
            while True:
                with _stream_ack_cv:
                    if key in _stream_cancels:
                        cancelled = True
                        break
                    if not budget or \
                            idx - _stream_acks.get(key, 0) < budget:
                        break
                await _asyncio.sleep(0.02)
            if cancelled:
                break
        _reply(("calldone", call_id,
                "cancelled" if cancelled else "ok_stream", idx))

    def _set_task_ctx(task_id_bin, name):
        worker_mod._task_context.current_task_id = (
            TaskID(task_id_bin) if task_id_bin else None)
        worker_mod._task_context.task_name = name
        # Feed the flight recorder's task-stuck watchdog: a task still
        # executing past flight_task_stuck_s auto-dumps this worker's
        # stacks without operator action (one `is None` branch when
        # the recorder is disarmed).
        from ray_tpu._private import flight as _flight

        if _flight._FLIGHT is not None:
            if task_id_bin:
                _flight.note_task_started(name or "task")
            else:
                _flight.note_task_finished()

    # ------------------------------------------------- streaming producers
    # Mux actors receive acks as ("stream_ack", tid_bin, n) REQUESTS on
    # the req channel (the main loop below drains it continuously); the
    # single-flight planes (task_stream / actor_stream) read the dedicated
    # ack channel inside their pause loop.
    _stream_acks: Dict[bytes, int] = {}
    _stream_cancels: set = set()
    _stream_ack_cv = _threading_mod.Condition()

    def _ack_chan_waiter(tid_bin: bytes, budget: int):
        """wait_acks over the dedicated ack channel (task_stream /
        actor_stream): drain opportunistically between yields, block at
        the budget. Stale acks from a previous stream on this worker are
        read and ignored (tid-tagged)."""
        acked = [0]
        cancelled = [False]

        def _drain(timeout: float) -> bool:
            if ack is None:
                return False
            try:
                m = ack.read(timeout=timeout)
            except ChannelTimeoutError:
                return False
            except ChannelError:
                cancelled[0] = True  # driver tore the channel down
                return False
            if m and m[0] == "stream_ack" and bytes(m[1]) == tid_bin:
                n = m[2]
                if n < 0:
                    cancelled[0] = True
                elif n > acked[0]:
                    acked[0] = n
            return True

        def wait_acks(count: int) -> bool:
            while _drain(0.001):
                pass
            while budget and count - acked[0] >= budget \
                    and not cancelled[0]:
                if not _drain(0.2) and os.getppid() == 1:
                    cancelled[0] = True  # orphaned: driver died
            return not cancelled[0]

        return wait_acks

    def _mux_ack_waiter(tid_bin: bytes, budget: int):
        """wait_acks over the main-loop-maintained ack table (mux
        actors: many streams share one worker process)."""
        key = bytes(tid_bin)

        def wait_acks(count: int) -> bool:
            with _stream_ack_cv:
                while True:
                    if key in _stream_cancels:
                        return False
                    if not budget or \
                            count - _stream_acks.get(key, 0) < budget:
                        return True
                    _stream_ack_cv.wait(0.2)
                    if os.getppid() == 1:
                        return False

        return wait_acks

    def _mux_stream_done(tid_bin: bytes):
        key = bytes(tid_bin)
        with _stream_ack_cv:
            _stream_acks.pop(key, None)
            _stream_cancels.discard(key)

    while True:
        try:
            msg = req.read(timeout=5.0)
        except ChannelTimeoutError:
            # Liveness escape hatch: if the parent died, exit.
            if os.getppid() == 1:
                return
            continue
        except ChannelError:
            return

        kind = msg[0]
        try:
            if kind == "exit":
                _reply(("ok", None))
                return
            elif kind == "ping":
                _reply(("ok", os.getpid()))
            elif kind == "task":
                (_, digest, fn_bytes, payload, return_keys, num_returns,
                 task_id_bin, name, env_fields) = msg[:9]
                trace_wire = msg[9] if len(msg) > 9 else None
                fn = fn_cache.get(digest)
                if fn is None:
                    fn = cloudpickle.loads(_fetch_blob(store, fn_bytes))
                    fn_cache[digest] = fn
                args, kwargs = _load_payload(store, ctx,
                                             _fetch_blob(store, payload))
                _set_task_ctx(task_id_bin, name)
                span = tracing.begin(
                    "worker.exec", parent=tracing.extract(trace_wire),
                    task=name) if trace_wire is not None else None
                try:
                    if env_fields:
                        renv = _cached_runtime_env(env_fields)
                        with renv.applied():
                            result = fn(*args, **kwargs)
                    else:
                        result = fn(*args, **kwargs)
                except BaseException:
                    tracing.finish(span, status="error")
                    span = None
                    raise
                finally:
                    tracing.finish(span)
                    _set_task_ctx(None, None)
                _store_outputs(store, ctx, return_keys, result, num_returns)
                _reply(("ok", None))
            elif kind == "actor_new":
                _, cls_bytes, payload = msg
                cls = cloudpickle.loads(_fetch_blob(store, cls_bytes))
                args, kwargs = _load_payload(store, ctx,
                                             _fetch_blob(store, payload))
                actor_instance = cls(*args, **kwargs)
                _reply(("ok", None))
            elif kind == "actor_new2":
                # Concurrent actor plane: async actors get a dedicated
                # asyncio loop thread, threaded actors a pool; calls arrive
                # as fire-and-forget "actor_submit" and complete out of
                # order as ("calldone", call_id, ...) on the reply channel.
                import threading as _threading

                _, cls_bytes, payload, mode, max_concurrency = msg
                cls = cloudpickle.loads(_fetch_blob(store, cls_bytes))
                args, kwargs = _load_payload(store, ctx,
                                             _fetch_blob(store, payload))
                actor_instance = cls(*args, **kwargs)
                actor_state["mode"] = mode
                if mode == "async":
                    import asyncio as _asyncio

                    loop = _asyncio.new_event_loop()
                    sem = _asyncio.Semaphore(max(int(max_concurrency), 1))

                    def _loop_main():
                        _asyncio.set_event_loop(loop)
                        loop.run_forever()

                    t = _threading.Thread(target=_loop_main, daemon=True,
                                          name="actor-async-loop")
                    t.start()
                    actor_state["loop"] = loop
                    actor_state["sem"] = sem
                else:
                    from concurrent.futures import ThreadPoolExecutor

                    actor_state["pool"] = ThreadPoolExecutor(
                        max_workers=max(int(max_concurrency), 1),
                        thread_name_prefix="actor-call")
                _reply(("ok", None))
            elif kind == "dag_exec":
                # Compiled-DAG shm plane (reference: do_exec_tasks over
                # NCCL/shm channels): run this actor's static stage
                # schedule INSIDE the worker, reading/writing native shm
                # channels directly — the driver never touches the
                # inter-stage payloads. Blocks until the DAG tears down
                # (channels closed), which is the "DAG occupies the
                # actor" semantic; the reply releases the caller.
                try:
                    desc = pickle.loads(_fetch_blob(store, msg[1]))
                    _run_dag_stages(store, desc, actor_instance)
                except BaseException:  # noqa: BLE001 — must not reach the
                    # outer error boundary: that would send a SECOND reply
                    # and desync every later request on this worker.
                    print("ray_tpu dag_exec setup failed:\n"
                          + traceback.format_exc(), file=sys.stderr,
                          flush=True)
                finally:
                    _reply(("ok", None))
            elif kind == "actor_submit":
                (_, call_id, method_name, payload, return_keys,
                 num_returns, task_id_bin, name) = msg[:8]
                stream_budget = msg[8] if len(msg) > 8 else None
                if actor_instance is None:
                    _fail_actor_call(call_id, name, RuntimeError(
                        "actor_submit before actor_new2"))
                elif actor_state.get("mode") == "async":
                    import asyncio as _asyncio

                    loop = actor_state["loop"]
                    sem = actor_state["sem"]

                    async def _gated(call_id=call_id,
                                     method_name=method_name,
                                     payload=payload,
                                     return_keys=return_keys,
                                     num_returns=num_returns,
                                     task_id_bin=task_id_bin, name=name,
                                     stream_budget=stream_budget):
                        async with sem:
                            await _run_actor_call_async(
                                call_id, method_name, payload, return_keys,
                                num_returns, task_id_bin, name,
                                stream_budget)

                    _asyncio.run_coroutine_threadsafe(_gated(), loop)
                else:
                    actor_state["pool"].submit(
                        _run_actor_call_sync, call_id, method_name,
                        payload, return_keys, num_returns, task_id_bin,
                        name, stream_budget)
            elif kind == "stream_ack":
                # Mux-actor backpressure: consumption watermark (n >= 0)
                # or cancel (n < 0) for one in-flight stream. Fire and
                # forget — no reply.
                _, tid_bin, n = msg
                key = bytes(tid_bin)
                with _stream_ack_cv:
                    if n < 0:
                        _stream_cancels.add(key)
                    elif n > _stream_acks.get(key, 0):
                        _stream_acks[key] = n
                    _stream_ack_cv.notify_all()
            elif kind == "task_stream":
                (_, digest, fn_bytes, payload, task_id_bin, name,
                 env_fields, budget) = msg
                fn = fn_cache.get(digest)
                if fn is None:
                    fn = cloudpickle.loads(_fetch_blob(store, fn_bytes))
                    fn_cache[digest] = fn
                args, kwargs = _load_payload(store, ctx,
                                             _fetch_blob(store, payload))
                _set_task_ctx(task_id_bin, name)
                try:
                    def _go():
                        gen = fn(*args, **kwargs)
                        total, was_cancelled = _run_stream_yields(
                            gen, ctx, max_msg, _stage_result,
                            lambda i, f: _reply(("item", i, f)),
                            budget,
                            _ack_chan_waiter(bytes(task_id_bin), budget))
                        _reply(("cancelled",) if was_cancelled
                               else ("ok", total))

                    if env_fields:
                        renv = _cached_runtime_env(env_fields)
                        with renv.applied():
                            _go()
                    else:
                        _go()
                finally:
                    _set_task_ctx(None, None)
            elif kind == "actor_stream":
                # Streaming method on a sync (non-mux) process actor: the
                # same wire shape as task_stream, generator from the
                # resident instance.
                (_, method_name, payload, task_id_bin, name, budget) = msg
                if actor_instance is None:
                    raise RuntimeError("actor_stream before actor_new")
                method = getattr(actor_instance, method_name)
                args, kwargs = _load_payload(store, ctx,
                                             _fetch_blob(store, payload))
                _set_task_ctx(task_id_bin, name)
                try:
                    gen = method(*args, **kwargs)
                    total, was_cancelled = _run_stream_yields(
                        gen, ctx, max_msg, _stage_result,
                        lambda i, f: _reply(("item", i, f)),
                        budget, _ack_chan_waiter(bytes(task_id_bin),
                                                 budget))
                    _reply(("cancelled",) if was_cancelled
                           else ("ok", total))
                finally:
                    _set_task_ctx(None, None)
            elif kind == "actor_call":
                (_, method_name, payload, return_keys, num_returns,
                 task_id_bin, name) = msg
                if actor_instance is None:
                    raise RuntimeError("actor_call before actor_new")
                method = getattr(actor_instance, method_name)
                args, kwargs = _load_payload(store, ctx,
                                             _fetch_blob(store, payload))
                _set_task_ctx(task_id_bin, name)
                try:
                    result = method(*args, **kwargs)
                finally:
                    _set_task_ctx(None, None)
                if return_keys:
                    _store_outputs(store, ctx, return_keys, result,
                                   num_returns)
                    _reply(("ok", None))
                else:
                    # Proxy apply (DAG exec loop): result rides the reply;
                    # big results stage through the store instead.
                    raw = ctx.serialize(result).to_bytes()
                    if len(raw) > max(max_msg // 4, 64 * 1024):
                        _reply(("okshm", _stage_result(raw)))
                    else:
                        _reply(("ok", raw))
            else:
                raise ValueError(f"unknown request kind {kind!r}")
        except BaseException as exc:  # noqa: BLE001 — worker error boundary
            if kind in ("actor_call", "actor_stream"):
                name = msg[1]
            elif kind == "task_stream":
                name = msg[5]
            else:
                name = "task"
            try:
                err = RayTaskError.from_exception(str(name), exc)
                _reply(("err", pickle.dumps(err)))
            except Exception:  # noqa: BLE001 — unpicklable cause fallback
                err = RayTaskError(str(name), traceback.format_exc(),
                                   cause=None)
                _reply(("err", pickle.dumps(err)))


_renv_cache = {}


def _cached_runtime_env(env_fields):
    """One staged RuntimeEnv per distinct env per worker process: staging
    copies working_dir into a tempdir, which must not repeat (or leak)
    per task execution."""
    import pickle as _pickle

    from ray_tpu.runtime_env import RuntimeEnv

    fields = {k: v for k, v in env_fields.items()
              if k in ("env_vars", "working_dir", "py_modules", "pip")}
    key = _pickle.dumps(sorted(fields.items()))
    renv = _renv_cache.get(key)
    if renv is None:
        renv = RuntimeEnv(**fields).stage()
        _renv_cache[key] = renv
    return renv


def _install_pdeathsig() -> None:
    """Orphan fence (Linux): a pooled worker must never outlive the
    process that owns its shm store — a SIGKILLed hosting daemon
    (chaos node kills, reaped nodes, the head-failover episode's
    teardown) would otherwise leave workers spinning against dead
    channels forever, observed as CPU-burning orphans. The kernel
    delivers SIGKILL on parent death (PR_SET_PDEATHSIG), installed by
    the child itself so the spawn path needs no fork-unsafe
    preexec_fn. The parent-died-before-prctl race is closed by
    comparing getppid() against the SPAWNER's pid handed down in
    RAY_TPU_PARENT_PID — never against init's pid 1, which is the
    legitimate parent when the hosting daemon runs as a container's
    PID 1. Silently a no-op off Linux."""
    if not sys.platform.startswith("linux"):
        return
    try:
        import ctypes
        import signal as _signal

        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        PR_SET_PDEATHSIG = 1
        libc.prctl(PR_SET_PDEATHSIG, int(_signal.SIGKILL), 0, 0, 0)
        spawner = os.environ.get("RAY_TPU_PARENT_PID")
        if spawner and os.getppid() != int(spawner):
            # Reparented before prctl landed: the spawner is already
            # gone and the death signal will never fire — exit now.
            os.kill(os.getpid(), _signal.SIGKILL)
    except Exception:  # noqa: BLE001 — fence is best-effort hardening
        pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--store", required=True)
    ap.add_argument("--req-id", type=int, required=True)
    ap.add_argument("--rep-id", type=int, required=True)
    ap.add_argument("--api-req-id", type=int, default=0)
    ap.add_argument("--api-rep-id", type=int, default=0)
    ap.add_argument("--ack-id", type=int, default=0)
    ap.add_argument("--worker-id", type=int, default=0)
    ap.add_argument("--max-msg", type=int, default=4 << 20)
    args = ap.parse_args(argv)
    _install_pdeathsig()
    # Tracing arms from the inherited environment; worker processes have
    # no dialable trace_dump server, so finished spans SPILL to the
    # hosting runtime's RAY_TPU_TRACE_DIR (merged by its trace_dump).
    tracing.install_from_env(component="worker", spill=True)
    # Flight recorder: same shape — bundle snapshots spill periodically
    # to the hosting runtime's RAY_TPU_FLIGHT_DIR (merged by its
    # debug_dump), since nothing can dial a worker process directly.
    from ray_tpu._private import flight

    flight.install_from_env(component="worker", spill=True)
    worker_loop(args.store, args.req_id, args.rep_id, args.worker_id,
                args.max_msg, args.api_req_id, args.api_rep_id,
                args.ack_id)
    return 0


if __name__ == "__main__":
    # Re-dispatch through the canonical import so _ShmRef has one class
    # identity (running under -m makes this module __main__, which would
    # otherwise break isinstance against driver-pickled markers).
    from ray_tpu._private import worker_main as _canonical

    sys.exit(_canonical.main())
