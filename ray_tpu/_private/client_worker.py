"""Client-mode worker runtime: the ``ray_tpu`` API inside a worker process.

Rebuild of the in-worker core-worker surface (reference role: the
CoreWorker every Ray worker process embeds, which proxies task submission
and object operations to its owner/raylet over RPC [unverified]). When
``worker_main`` boots, it installs a ``ClientWorker`` as the process-global
worker, so user task code calling ``ray_tpu.get/put/remote/...`` transparently
forwards over the per-worker API channel to the driver's
``driver_service`` instead of booting a second full runtime in the worker.

Single-threaded protocol: a lock serializes requests; replies need no
correlation ids. Oversized values ride the shm object store.
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Dict, List, Optional

from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID, \
    WorkerID, _Counter
from ray_tpu._private.serialization import SerializationContext, \
    SerializedObject
from ray_tpu.exceptions import ChannelTimeoutError, RayTaskError, RayTpuError

_INLINE_LIMIT = 256 * 1024  # headroom under the 1MB channel capacity


class _NullRefTable:
    """ObjectRef ref-count shim: the driver's service pins objects for this
    worker's lifetime, so client-side counting is a no-op."""

    def add_local_ref(self, oid):
        pass

    def remove_local_ref(self, oid):
        pass

    def on_ready(self, oid, callback):
        raise RayTpuError(
            "ObjectRef.future()/await is not supported inside worker "
            "processes; use ray_tpu.get()")


class ClientWorker:
    """Thin worker-process runtime that proxies the API to the driver."""

    def __init__(self, shm_store, api_req, api_rep, worker_id: int):
        self.is_alive = True
        self._shm = shm_store
        self._req = api_req
        self._rep = api_rep
        self._lock = threading.Lock()
        self._client_worker_id = worker_id
        self.store = _NullRefTable()
        self.serialization_context = SerializationContext()
        self.submission_counter = _Counter()
        self.put_counter = _Counter()
        self._stage_counter = _Counter()
        self.worker_id = WorkerID.from_random()
        self._ctx: Optional[dict] = None  # fetched lazily: the driver's
        # runtime may still be booting while this process starts up.

    def _driver_ctx(self) -> dict:
        if self._ctx is None:
            self._ctx = self._request(("api_ctx",))
        return self._ctx

    @property
    def job_id(self) -> JobID:
        return JobID(self._driver_ctx()["job_id"])

    @property
    def node_id(self) -> NodeID:
        return NodeID(self._driver_ctx()["node_id"])

    @property
    def namespace(self) -> str:
        return self._driver_ctx()["namespace"]

    @property
    def driver_task_id(self) -> TaskID:
        return TaskID.for_driver(self.job_id)

    # ------------------------------------------------------------ transport
    def _request(self, msg: tuple, timeout: float = 300.0):
        raw = pickle.dumps(msg, protocol=5)
        if len(raw) > _INLINE_LIMIT:
            # Oversized request (big kv value / task payload): ship the
            # whole pickled message through the store instead of the
            # channel.
            key = self._stage_key()
            self._shm.put(key, raw)
            msg = ("api_blob", key)
        with self._lock:
            self._req.write(msg, timeout=30.0)
            status, value = self._rep.read(timeout=timeout)
        if status == "okshm_reply":  # oversized reply: whole tuple staged
            raw = bytes(self._shm.get(value))
            self._shm.delete(value)
            status, value = pickle.loads(raw)
        if status == "err":
            exc = pickle.loads(value)
            raise exc
        if status == "okshm":
            data = bytes(self._shm.get(value))
            self._shm.delete(value)
            return data
        return value

    def _stage_key(self) -> int:
        # Disjoint fields: prefix bits 52-63, worker id bits 32-51,
        # counter bits 0-31 (an id ORed into the prefix nibble would alias
        # keys across workers 4096 apart).
        return ((0xA4B << 52)
                | (self._client_worker_id & 0xF_FFFF) << 32
                | (self._stage_counter.next() & 0xFFFF_FFFF))

    # ------------------------------------------------------------ task ctx
    def current_task_id(self) -> TaskID:
        from ray_tpu._private.worker import _task_context

        tid = getattr(_task_context, "current_task_id", None)
        return tid if tid is not None else self.driver_task_id

    def next_task_id(self) -> TaskID:
        return TaskID.of(self.current_task_id(),
                         self.submission_counter.next())

    # ------------------------------------------------------------------ api
    def put_object(self, value: Any):
        from ray_tpu._private.worker import ObjectRef

        if isinstance(value, ObjectRef):
            raise TypeError(
                "Calling put() on an ObjectRef is not allowed; pass the ref "
                "directly instead.")
        oid = ObjectID.for_put(self.current_task_id(),
                               self.put_counter.next())
        data = self.serialization_context.serialize(value).to_bytes()
        if len(data) > _INLINE_LIMIT:
            key = self._stage_key()
            self._shm.put(key, data)
            self._request(("api_put", oid.binary(), key, True))
        else:
            self._request(("api_put", oid.binary(), data, False))
        return ObjectRef(oid)

    def get_object(self, ref, timeout: Optional[float] = None):
        data = self._request(
            ("api_get", ref.object_id.binary(), timeout),
            timeout=(timeout + 30.0) if timeout is not None else 3600.0)
        value = self.serialization_context.deserialize(
            SerializedObject.from_bytes(data))
        if isinstance(value, RayTaskError):
            raise value.as_instanceof_cause()
        return value

    def wait(self, object_ids: List[ObjectID], num_returns: int,
             timeout: Optional[float]):
        ready, not_ready = self._request(
            ("api_wait", [o.binary() for o in object_ids], num_returns,
             timeout),
            timeout=(timeout + 30.0) if timeout is not None else 3600.0)
        return ([ObjectID(b) for b in ready], [ObjectID(b) for b in not_ready])

    def submit_task(self, spec) -> List[Any]:
        import cloudpickle

        from ray_tpu._private.worker import ObjectRef

        self._request(("api_submit", cloudpickle.dumps(spec)))
        return [ObjectRef(oid) for oid in spec.return_ids]

    def actor_submit(self, actor_id: ActorID, method_name: str, args, kwargs,
                     num_returns: int, name: str) -> List[Any]:
        import cloudpickle

        from ray_tpu._private.worker import ObjectRef

        oid_bins = self._request(
            ("api_actor_submit", actor_id.binary(), method_name,
             cloudpickle.dumps((args, kwargs)), num_returns, name))
        return [ObjectRef(ObjectID(b)) for b in oid_bins]

    def actor_create(self, cls: type, args, kwargs,
                     opts: Dict[str, Any]) -> ActorID:
        import cloudpickle

        actor_bin = self._request(
            ("api_actor_create", cloudpickle.dumps(cls),
             cloudpickle.dumps((args, kwargs)), dict(opts or {})))
        return ActorID(actor_bin)

    def actor_named(self, name: str, namespace: Optional[str]) -> ActorID:
        return ActorID(self._request(("api_actor_named", name, namespace)))

    @property
    def resource_pool(self):
        """Shim so resource introspection APIs work inside workers."""

        class _Pool:
            def available(_self):
                return self._request(("api_resources", "available"))

            @property
            def total(_self):
                return self._request(("api_resources", "total"))

        return _Pool()

    # ------------------------------------------------------------------- kv
    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True):
        op = "put" if overwrite else "put_once"
        return self._request(("api_kv", op, key, value))

    def kv_get(self, key: bytes):
        return self._request(("api_kv", "get", key, None))

    def kv_del(self, key: bytes):
        return self._request(("api_kv", "del", key, None))

    def kv_keys(self, prefix: bytes = b""):
        return self._request(("api_kv", "keys", prefix, None))

    def shutdown(self):
        self.is_alive = False


class ClientActorHandle:
    """Actor handle rehydrated inside a worker process: method calls
    forward to the driver, which routes them to the actor's runtime."""

    def __init__(self, actor_id: ActorID, class_name: str = "Actor"):
        self._actor_id = actor_id
        self._class_name = class_name

    @property
    def _ray_actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, item: str):
        if item.startswith("_"):
            raise AttributeError(item)
        return _ClientActorMethod(self, item)

    def __reduce__(self):
        from ray_tpu.actor import _rebuild_handle

        return (_rebuild_handle, (self._actor_id,))

    def __repr__(self):
        return (f"ClientActorHandle({self._class_name}, "
                f"{self._actor_id.hex()[:12]}…)")


class _ClientActorMethod:
    def __init__(self, handle: ClientActorHandle, method_name: str,
                 options: Optional[Dict[str, Any]] = None):
        self._handle = handle
        self._method_name = method_name
        self._options = dict(options or {})

    def options(self, **opts) -> "_ClientActorMethod":
        merged = dict(self._options)
        merged.update(opts)
        return _ClientActorMethod(self._handle, self._method_name, merged)

    def remote(self, *args, **kwargs):
        from ray_tpu._private.worker import global_worker

        worker = global_worker()
        num_returns = self._options.get("num_returns", 1)
        name = self._options.get(
            "name", f"{self._handle._class_name}.{self._method_name}")
        refs = worker.actor_submit(
            self._handle._actor_id, self._method_name, args, kwargs,
            num_returns, name)
        return refs[0] if num_returns == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._method_name!r} cannot be called directly; "
            f"use .remote().")
