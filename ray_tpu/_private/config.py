"""Typed config/flag registry with environment-variable override.

TPU-native rebuild of the reference's RayConfig flag system (reference:
src/ray/common/ray_config_def.h [unverified]): every knob is declared once
with a type and default, overridable via ``RAY_TPU_<NAME>`` environment
variables or a ``_system_config`` dict passed to ``init()``.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict

_ENV_PREFIX = "RAY_TPU_"


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: int,
    float: float,
    str: str,
    dict: json.loads,
    list: json.loads,
}


@dataclass
class _Flag:
    name: str
    type: type
    default: Any
    doc: str = ""


class ConfigRegistry:
    """Declare-once flag registry; values resolve env > override > default."""

    def __init__(self):
        self._flags: Dict[str, _Flag] = {}
        self._overrides: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def declare(self, name: str, type_: type, default: Any, doc: str = ""):
        with self._lock:
            if name in self._flags:
                raise ValueError(f"flag {name!r} declared twice")
            self._flags[name] = _Flag(name, type_, default, doc)

    def get(self, name: str) -> Any:
        flag = self._flags[name]
        env_val = os.environ.get(_ENV_PREFIX + name.upper())
        if env_val is not None:
            return _PARSERS[flag.type](env_val)
        with self._lock:
            if name in self._overrides:
                return self._overrides[name]
        return flag.default

    def set(self, name: str, value: Any):
        flag = self._flags[name]
        if not isinstance(value, flag.type):
            value = _PARSERS[flag.type](str(value))
        with self._lock:
            self._overrides[name] = value

    def apply_system_config(self, system_config: Dict[str, Any]):
        for k, v in (system_config or {}).items():
            if k not in self._flags:
                raise ValueError(f"unknown system config flag {k!r}")
            self.set(k, v)

    def reset(self):
        with self._lock:
            self._overrides.clear()

    def describe(self) -> Dict[str, Any]:
        return {
            name: {"type": f.type.__name__, "default": f.default,
                   "value": self.get(name), "doc": f.doc}
            for name, f in sorted(self._flags.items())
        }

    def __getattr__(self, name: str) -> Any:
        # Attribute-style access for declared flags.
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self.get(name)
        except KeyError:
            raise AttributeError(name) from None


GlobalConfig = ConfigRegistry()

# --- Core runtime flags (mirrors the role of ray_config_def.h) -------------
_D = GlobalConfig.declare
_D("task_max_retries", int, 3, "Default max retries for retriable tasks.")
_D("actor_max_restarts", int, 0, "Default max actor restarts.")
_D("inline_object_max_bytes", int, 100 * 1024,
   "Objects at or under this size are stored inline in the task reply.")
_D("object_store_memory_bytes", int, 512 * 1024 * 1024,
   "In-process object store soft cap before spilling to disk.")
_D("object_spill_dir", str, "",
   "Directory for spilled objects ('' = <session_dir>/spill).")
_D("worker_pool_size", int, 0,
   "Thread workers for local task execution (0 = num_cpus).")
_D("get_timeout_warning_s", float, 10.0,
   "Warn if a blocking get waits longer than this.")
_D("wave_executor_max_args", int, 4,
   "Max padded arg slots per task in the JAX wave executor.")
_D("wave_executor_dynamic", bool, False,
   "Use dynamic frontier while_loop instead of static level schedule.")
_D("channel_buffer_bytes", int, 1024 * 1024,
   "Default mutable-channel buffer size.")
_D("channel_read_timeout_s", float, 60.0, "Channel read timeout.")
_D("health_check_period_s", float, 1.0, "Control-plane health check period.")
_D("health_check_failure_threshold", int, 5,
   "Missed health checks before a node is marked dead.")
_D("metrics_export_port", int, 0, "Prometheus scrape port (0 = disabled).")
_D("task_events_max_buffer", int, 100_000,
   "Ring-buffer capacity for task state events (state API/timeline).")
_D("scheduler_spread_threshold", float, 0.5,
   "Hybrid policy: pack until node utilization passes this, then spread.")
_D("scheduler_top_k_fraction", float, 0.2,
   "Hybrid policy: random tie-break among top-k fraction of nodes.")
_D("lineage_pinning_enabled", bool, True,
   "Keep task specs for lineage reconstruction of lost objects.")
_D("enable_timeline", bool, True, "Record task profile events for timeline.")
_D("shm_store_bytes", int, 256 * 1024 * 1024,
   "Shared-memory store segment size for the native object store.")
_D("shm_store_slots", int, 4096,
   "Max concurrent objects in the native shared-memory store.")
_D("use_native_queue", bool, True,
   "Route task dependency tracking through the C++ ready-ring when the "
   "native layer is available.")
_D("worker_mode", str, "process",
   "Task execution plane: 'process' (spawned worker processes over the shm "
   "store — the default, matching the reference's process-isolated "
   "workers) or 'thread' (in-process pool; used automatically when the "
   "native layer is unavailable).")
_D("memory_monitor_threshold", float, 0.95,
   "System memory-used fraction above which the monitor kills the "
   "youngest running process task (OutOfMemoryError, retriable). "
   "0 disables the monitor.")
_D("spill_backlog_factor", float, 4.0,
   "Route tasks to remote node daemons when the local backlog exceeds "
   "factor times num_cpus and a feasible node is less loaded.")
_D("dep_wait_s", float, 300.0,
   "Bound on waiting for a task's dependency to be produced: the "
   "driver-side wait before inlining a local value, and the node-side "
   "pull-wait for a pending pull-ref shipped ahead of its producer. "
   "Raises GetTimeoutError past it (RAY_TPU_DEP_WAIT_S).")
_D("direct_dispatch", bool, True,
   "Push tasks peer-to-peer to the target node daemon's direct server "
   "(batched over the framed transport), falling back to a head-relayed "
   "task_push only when the direct dial fails.")
_D("locality_min_bytes", int, 64 * 1024,
   "Locality-aware placement: prefer the feasible node already holding "
   "at least this many bytes of a task's ref args over the least-loaded "
   "node (pending deps count as presence at their target node).")
_D("locality_load_slack", float, 8.0,
   "Locality-aware placement: the bytes-resident node wins only while "
   "its load is within this many backlog-per-CPU units of the "
   "least-loaded feasible node (past it, spread wins over locality).")
_D("external_pull_ttl_s", float, 600.0,
   "Bound on post-completion pull retries for remote actor-task results "
   "(mirrors the ActorHost result-pin TTL): past it the object is "
   "declared lost instead of retrying forever.")
_D("generator_backpressure_items", int, 0,
   "Consumer-driven backpressure for num_returns='streaming' generator "
   "tasks: the producer's yield loop pauses while this many committed "
   "items remain unconsumed, resuming on consumption acks "
   "(RAY_TPU_GENERATOR_BACKPRESSURE_ITEMS; 0 = unlimited).")
_D("transport_handshake_timeout_s", float, 5.0,
   "Server-side bound on the transport HMAC handshake: a connect-then-"
   "hang or half-open peer is dropped after this many seconds instead "
   "of pinning its handshake thread (the accept loop itself is never "
   "blocked — handshakes run per-connection).")
_D("peer_pull_attempts", int, 3,
   "Direct peer chunk pulls retry (re-dialing a fresh lane) up to this "
   "many times with jittered exponential backoff before the puller "
   "gives up on the peer and falls back / declares the object lost — "
   "bounded reconnect under chaos-induced resets.")
_D("peer_pull_backoff_s", float, 0.05,
   "Base backoff between peer pull attempts (doubled per attempt, "
   "jittered x0.5-1.5 so synchronized pullers don't stampede a "
   "recovering peer).")
_D("worker_channel_bytes", int, 1024 * 1024,
   "Request/reply channel buffer size per worker process (4 channels per "
   "worker are resident in the shm store; larger blobs are staged as "
   "regular shm objects instead of widening the channels).")
_D("log_level", str, "warning",
   "Threshold for the ray_tpu logger hierarchy (debug/info/warning/"
   "error). Daemon loops log swallowed transient failures at debug; "
   "survivable-but-unexpected conditions at warning.")
_D("head_client_timeout_s", float, 5.0,
   "Per-request timeout for short head-service RPCs issued by "
   "tooling/state clients (the CLI, dashboards); the long-lived "
   "HeadClient channels use their own reconnect-and-resume protocol.")
_D("workflow_storage", str, "",
   "Default workflow storage root URI ('' = ~/.ray_tpu/workflows; "
   "supports local paths, memory://, and fsspec URIs).")
_D("runtime_env_cache", str, "",
   "Directory for built runtime-env (pip) environments "
   "('' = ~/.cache/ray_tpu/runtime_envs).")
_D("native_cache", str, "",
   "Directory for compiled native-layer artifacts "
   "('' = ~/.cache/ray_tpu).")
_D("coordinator_address", str, "",
   "Multi-process device-plane coordinator address for "
   "parallel.distributed.initialize ('' = single-process mesh).")
_D("ownership_directory", bool, True,
   "Ownership-based object directory: node daemons skip the per-object "
   "steady-state object_announce to the head (locations flow to the "
   "owning driver in the direct task_done/item_done reports; peers "
   "resolve owner-direct over the p2p plane), and an exiting driver "
   "lease-transfers its table to the head. Off = every completion "
   "announces to the head (the pre-ownership centralized directory).")
_D("head_log_compact_records", int, 50000,
   "Compact the head's append-only state log once it holds this many "
   "records (snapshot + truncate; 0 disables compaction).")
_D("autoscaler_launch_retries", int, 3,
   "Provider node launches retry up to this many times (jittered "
   "exponential backoff) before the autoscaler surfaces a typed "
   "NodeLaunchFailedError instead of silent membership absence.")
_D("autoscaler_launch_backoff_s", float, 0.5,
   "Base backoff between node-launch attempts (doubled per attempt, "
   "jittered x0.5-1.5 so concurrent launch storms spread).")
_D("autoscaler_launch_grace_s", float, 60.0,
   "Grace window for a LAUNCHING node: from process start until this "
   "many seconds pass, a node absent from head membership is treated "
   "as still cold-starting, never as dead — slow engine/runtime init "
   "must not be reaped by the liveness plane mid-boot.")
_D("autoscaler_drain_timeout_s", float, 15.0,
   "Drain-before-reap bound: an idle node chosen for reap waits up to "
   "this long for in-flight tasks to finish and node-held result "
   "bytes to lease-transfer (object_offload to their owner + "
   "object_transfer re-point of head fallback entries) before the "
   "provider terminates it.")
_D("trace_max_spans", int, 65536,
   "Per-process span ring capacity for the distributed tracing plane "
   "(RAY_TPU_TRACE arms tracing; off = zero spans, zero wire bytes).")
_D("trace_cold_start_window_s", float, 180.0,
   "How long a launched node daemon keeps RAY_TPU_TRACE_PARENT in its "
   "environment: worker processes spawned inside the window parent "
   "their init spans to the launching request's trace; past it the "
   "variable is dropped so later unrelated scale-ups on a long-lived "
   "node aren't misattributed to a finished trace.")
_D("profile_hz", float, 19.0,
   "Flight-recorder stack sampler frequency (RAY_TPU_PROFILE arms the "
   "sampler; the interval is jittered ±50% so periodic work isn't "
   "phase-locked out of the profile). The default budgets an always-"
   "on sampler under ~1% of one core at typical control-plane thread "
   "counts (one sweep over ~40 threads measures ~0.5 ms, and the GIL "
   "serializes the sweep against user code).")
_D("profile_max_stacks", int, 2048,
   "Bound on DISTINCT folded stacks the sampler aggregates; overflow "
   "counts into stacks_dropped instead of growing memory.")
_D("flight_event_capacity", int, 4096,
   "Per-process flight-recorder event ring capacity (state "
   "transitions, queue depths, lock-hold outliers, GC pauses).")
_D("flight_dir", str, "",
   "Directory for flight bundles: watchdog auto-dumps and worker-"
   "process bundle spills ('' = <session_dir>/flight, injected into "
   "spawned processes via RAY_TPU_FLIGHT_DIR).")
_D("flight_gc_ms", float, 20.0,
   "GC pauses at or above this many milliseconds become gc.pause "
   "events in the flight ring (gc.callbacks hook; a classic "
   "invisible source of tail latency).")
_D("flight_lock_hold_ms", float, 50.0,
   "Tracked-lock hold time above which the release records a "
   "lock.hold outlier event in the flight ring.")
_D("flight_lock_watchdog_s", float, 10.0,
   "Tracked-lock hold time above which the lock-hold watchdog fires "
   "an automatic local dump (the observable shape of a deadlock or a "
   "lock held across blocking I/O).")
_D("flight_heartbeat_gap_s", float, 30.0,
   "Gap since the last flight.beat() above which the heartbeat-gap "
   "watchdog fires an automatic local dump (one fire per gap "
   "episode; beats resuming re-arm it).")
_D("flight_loop_lag_s", float, 2.0,
   "Watchdog-loop wake overshoot above which the event-loop-lag "
   "watchdog fires: no thread getting scheduled for this long is a "
   "process-wide stall (GIL hog, swap storm, SIGSTOP).")
_D("flight_watchdog_period_s", float, 1.0,
   "Flight watchdog check period (also the event-loop-lag probe's "
   "expected sleep).")
_D("flight_dump_min_interval_s", float, 5.0,
   "Rate limit between watchdog auto-dumps: a flapping watchdog must "
   "not fill the disk with incident files.")
_D("flight_spill_period_s", float, 5.0,
   "Worker-process bundle spill period (jittered ±20%): nothing can "
   "dial a worker, so its hosting daemon merges these snapshots into "
   "its own debug_dump answer.")
_D("flight_spill_max_records", int, 8,
   "Rotate-at-capacity bound on a worker's bundle spill file: past "
   "this many snapshot lines the file restarts at the newest window, "
   "so a long-lived pooled worker spills O(capacity), not O(run). "
   "Merge reads only the NEWEST snapshot; the short history exists "
   "for manual forensics on a worker that died mid-incident, so keep "
   "this small — every line is a full bundle.")
_D("flight_task_stuck_s", float, 300.0,
   "An executing task (worker process / executor thread) past this "
   "bound fires the task-stuck watchdog — a hung worker auto-dumps "
   "without operator action (diagnostics only, never a kill; one "
   "fire per task).")
_D("flight_bundle_stale_s", float, 120.0,
   "Spilled worker bundles older than this are expired at merge "
   "time: a file left by an exited or re-leased pooled worker must "
   "not masquerade as a live process in an assembled incident.")
_D("serve_wake_timeout_s", float, 30.0,
   "Scale-to-zero wake bound: a request arriving at a deployment with "
   "zero replicas queues while the controller scales it back up, and "
   "fails typed only past this many seconds.")
_D("head_addresses", str, "",
   "Comma-separated head addresses, primary first then standbys "
   "(RAY_TPU_HEAD_ADDRESSES). Merged into every HeadClient's dial "
   "list and inherited by spawned node daemons, so the whole process "
   "tree learns the standby list and fails over without restarts "
   "('' = only the address passed to init/--address).")
_D("head_standby_probe_period_s", float, 1.0,
   "Warm-standby probe period: how often the standby head probes the "
   "primary's request channel before deciding it is dead.")
_D("head_standby_misses_to_promote", int, 3,
   "Consecutive failed standby probes before the standby promotes "
   "itself over the shared state log (promotion still waits on the "
   "log's flock fence — a stalled-but-alive primary blocks it).")
_D("head_dial_timeout_s", float, 5.0,
   "Per-address TCP dial bound when (re)connecting to a head: a "
   "client failing over walks its address list paying at most this "
   "much per unreachable standby (the heartbeat loop's re-dial budget "
   "rides the same bound).")
_D("head_failover_wait_s", float, 20.0,
   "How long in-flight head RPCs retry across a head blackout: the "
   "request coalescer replays unacked idempotent batches against "
   "re-dials (standby promotion window) up to this bound before "
   "failing callers; non-replayable relays fail immediately with "
   "HeadFailedOverError.")
_D("llm_kv_publish_ttl_s", float, 30.0,
   "Disaggregated serving publish TTL: a prefill replica's exported KV "
   "blocks (held for a decode replica's p2p pull) free automatically "
   "this many seconds after publication if never acked — a crashed or "
   "rerouted decode side can never leak prefill-pool blocks.")
_D("llm_disagg_pull_timeout_s", float, 10.0,
   "Disaggregated serving p2p pull bound: how long a decode replica "
   "waits for a published KV payload before abandoning the graft and "
   "transparently re-prefilling locally (typed fallback, not a hang).")
_D("llm_disagg_prefill_timeout_s", float, 30.0,
   "Disaggregated serving prefill RPC bound: how long the pairing "
   "layer waits for a prefill replica's ticket before falling back to "
   "the colocated path on the decode pool.")
