"""Global worker: init/shutdown, ObjectRef, get/put/wait/cancel.

Rebuild of the reference's worker core (reference:
python/ray/_private/worker.py + the Cython CoreWorker it wraps [unverified]).
One process-global ``Worker`` owns the serialization context, object store,
local scheduler, actor registry, and task-event buffer; ``init()`` boots it
and ``shutdown()`` tears it down. ObjectRefs count local references on
construction/destruction (owner-side refcounting).
"""

from __future__ import annotations

import atexit
import os
import tempfile
import threading
import uuid
from typing import Any, Dict, List, Optional, Union

from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.ids import (
    JobID,
    NodeID,
    ObjectID,
    TaskID,
    WorkerID,
    _Counter,
)
from ray_tpu._private.object_store import ObjectStore
from ray_tpu._private.scheduler import LocalScheduler, ResourcePool, TaskSpec
from ray_tpu._private.log import get_logger
from ray_tpu._private.serialization import SerializationContext
from ray_tpu._private.task_events import TaskEventBuffer
from ray_tpu._private import tracing
from ray_tpu.exceptions import RayTaskError, RayTpuError

log = get_logger(__name__)

class _TaskContext:
    """Per-execution task context. Backed by contextvars rather than
    threading.local so ASYNC actor calls — many coroutines interleaving
    on one event-loop thread — each see their own task id across await
    points (asyncio tasks run in copied contexts). For plain threads the
    semantics match threading.local: each thread's sets are isolated."""

    __slots__ = ("_tid", "_name")

    def __init__(self):
        import contextvars

        object.__setattr__(self, "_tid", contextvars.ContextVar(
            "ray_tpu_task_id", default=None))
        object.__setattr__(self, "_name", contextvars.ContextVar(
            "ray_tpu_task_name", default=None))

    @property
    def current_task_id(self):
        return self._tid.get()

    @current_task_id.setter
    def current_task_id(self, value):
        self._tid.set(value)

    @property
    def task_name(self):
        return self._name.get()

    @task_name.setter
    def task_name(self, value):
        self._name.set(value)


_task_context = _TaskContext()


class ObjectRef:
    """Future handle to a task output or put object.

    Pickling an ObjectRef registers the serialization with the owner store so
    the object stays alive while borrowed (simplified borrower protocol).
    """

    __slots__ = ("object_id", "_owner", "__weakref__")

    def __init__(self, object_id: ObjectID, _add_ref: bool = True):
        self.object_id = object_id
        self._owner = _try_global_worker()
        if _add_ref and self._owner is not None:
            self._owner.store.add_local_ref(object_id)

    def hex(self) -> str:
        return self.object_id.hex()

    def task_id(self) -> TaskID:
        return self.object_id.task_id()

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()
        worker = global_worker()

        def _done():
            try:
                fut.set_result(worker.get_object(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        worker.store.on_ready(self.object_id, _done)
        return fut

    def __await__(self):
        import asyncio

        loop = asyncio.get_event_loop()
        afut = loop.create_future()
        worker = global_worker()

        def _done():
            def _set():
                if afut.cancelled():
                    return
                try:
                    afut.set_result(worker.get_object(self))
                except BaseException as e:  # noqa: BLE001
                    afut.set_exception(e)

            loop.call_soon_threadsafe(_set)

        worker.store.on_ready(self.object_id, _done)
        return afut.__await__()

    def __reduce__(self):
        w = _try_global_worker()
        owner_info = None
        if w is not None:
            # Borrowed: keep alive for the borrower's lifetime (simplified —
            # the reference tracks borrowers and releases on their exit).
            w.store.add_local_ref(self.object_id)
            # Ownership model: a serialized ref carries its OWNER's
            # identity + direct address, so a foreign deserializer
            # resolves/subscribes owner-direct instead of polling the
            # head. A ref this runtime itself borrowed propagates the
            # ORIGINAL owner, not the forwarder. (Process-plane worker
            # stubs have no head client — their refs stay owner-less.)
            hc = getattr(w, "head_client", None)
            if hc is not None:
                owner_info = w.borrowed_owner(self.object_id.binary()) \
                    or (hc.client_id, list(hc._object_server.address))
        return (_deserialize_ref, (self.object_id, owner_info))

    def __del__(self):
        w = self._owner
        if w is not None and w.is_alive:
            try:
                w.store.remove_local_ref(self.object_id)
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass

    def __hash__(self):
        return hash(self.object_id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.object_id == self.object_id

    def __repr__(self):
        return f"ObjectRef({self.object_id.hex()[:16]}…)"


def _deserialize_ref(object_id: ObjectID, owner_info=None) -> ObjectRef:
    w = _try_global_worker()
    if owner_info is not None and w is not None \
            and getattr(w, "head_client", None) is not None \
            and owner_info[0] != w.head_client.client_id:
        w.record_borrowed_owner(object_id.binary(), owner_info)
    return ObjectRef(object_id, _add_ref=False)


class ObjectRefGenerator:
    """Iterator over the item ObjectRefs of a ``num_returns="streaming"``
    task (reference parity: ``ObjectRefGenerator``). Each ``next()``
    blocks only until the NEXT yield's object commits — locally, or via
    its ``item_done`` report from the executing node — not until the
    whole task finishes; returning a ref counts as CONSUMPTION for the
    producer's backpressure budget. ``close()`` (or dropping the
    generator) cancels the in-flight task and releases
    committed-but-unconsumed items. Mid-stream producer death surfaces
    the typed error at the next ``next()`` (after lineage replay, if
    any, is exhausted)."""

    def __init__(self, task_id: TaskID, worker: "Worker"):
        from ray_tpu._private.streaming import stream_end_id

        self._task_id = task_id
        self._worker = worker
        self._stream = worker.streams.get_or_create(task_id)
        self._index = 0
        self._end_oid = stream_end_id(task_id)
        self._end_ref = ObjectRef(self._end_oid)
        self._pending_ref: Optional[ObjectRef] = None
        self._total: Optional[int] = None
        self._closed = False

    # ------------------------------------------------------------ iteration
    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        ref = self._next(block=True)
        assert ref is not None
        return ref

    def try_next(self) -> Optional[ObjectRef]:
        """Non-blocking ``next()``: the next item's ref if it is already
        committed locally, else None. Raises StopIteration / the task's
        typed error exactly like ``next()``."""
        return self._next(block=False)

    def completed(self) -> ObjectRef:
        """The stream's END MARKER ref: ready when the whole generator
        task finished (value = total yield count; errors raise)."""
        return self._end_ref

    def wait_refs(self) -> List[ObjectRef]:
        """Refs to pass to ``ray_tpu.wait`` for "the next ``next()``
        would make progress": the NEXT item's ref plus the end marker.
        Lets a scheduler multiplex many streams without blocking on any
        single one."""
        return [self._item_ref(), self._end_ref]

    @property
    def task_id(self) -> TaskID:
        return self._task_id

    def _item_ref(self) -> ObjectRef:
        """The (cached) ref for the CURRENT index — handed out by the
        next successful ``next()``, so creating it early (for waits)
        leaks nothing."""
        from ray_tpu._private.streaming import stream_item_id

        if self._pending_ref is None:
            self._pending_ref = ObjectRef(
                stream_item_id(self._task_id, self._index))
        return self._pending_ref

    def _read_total(self) -> int:
        """The committed end marker: total count, or the task's typed
        error re-raised."""
        serialized = self._worker.store.get(self._end_oid, timeout=5.0)
        value = self._worker.serialization_context.deserialize(serialized)
        if isinstance(value, RayTaskError):
            raise value.as_instanceof_cause()
        return int(value)

    def _free_unconsumed(self):
        """Release committed-but-unconsumed item payloads (everything
        from the consumer's cursor up to the committed/total high-water
        mark) — the shared teardown step of close() and _fail_closed()."""
        from ray_tpu._private.streaming import stream_item_id

        upper = self._stream.committed
        if self._total is not None:
            upper = max(upper, self._total)
        drop = [stream_item_id(self._task_id, i)
                for i in range(self._index, upper)]
        if drop:
            self._worker.store.free(drop)

    def _fail_closed(self):
        """Error-path teardown: the task already finished or failed, so
        there is nothing to cancel — but committed-but-unconsumed item
        payloads and the stream's registry entry must still go, or every
        errored stream pins them forever. Marks the generator closed so
        close()/__del__ become no-ops."""
        self._closed = True
        try:
            if self._worker.is_alive:
                self._free_unconsumed()
        except Exception:  # noqa: BLE001 — cleanup must not mask the error
            pass
        finally:
            self._release_stream()

    def _next(self, block: bool) -> Optional[ObjectRef]:
        import time as _time

        if self._closed:
            raise StopIteration
        store = self._worker.store
        end_grace: Optional[float] = None
        while True:
            item = self._item_ref()
            oid = item.object_id
            if store.is_ready(oid):
                err = store.peek_error(oid)
                if err is not None:
                    self._fail_closed()
                    if hasattr(err, "as_instanceof_cause"):
                        raise err.as_instanceof_cause()
                    raise err
                self._pending_ref = None
                self._index += 1
                self._stream.advance_consumed(self._index)
                return item
            if self._total is None and store.is_ready(self._end_oid):
                try:
                    self._total = self._read_total()
                except BaseException:
                    self._fail_closed()
                    raise
            if self._total is not None and self._index >= self._total:
                self._closed = True
                self._release_stream()
                raise StopIteration
            if not block:
                return None
            # Remote streams: a large item's bytes stayed on the
            # producing node (announce + pull) — drive the transfer.
            router = self._worker.remote_router
            if router is not None and router.handles(oid) and \
                    self._index in self._stream.known_remote_sizes:
                router.prefetch(oid)
            if self._total is not None:
                # Task DONE but item i < total is not local: its bytes
                # are still in flight (pull) — or lost with no producer
                # left. Bound the wait so a lost item cannot hang us.
                if end_grace is None:
                    end_grace = _time.monotonic() + (
                        30.0 if router is not None else 5.0)
                elif _time.monotonic() > end_grace:
                    from ray_tpu.exceptions import ObjectLostError

                    self._fail_closed()
                    raise ObjectLostError(
                        f"streaming item {self._index} of task "
                        f"{self._task_id.hex()[:16]}… completed but its "
                        f"bytes are no longer retrievable")
            store.wait([oid, self._end_oid], 1, 0.2)

    # ------------------------------------------------------------ lifecycle
    def close(self):
        """Cancel the in-flight generator task and release
        committed-but-unconsumed items. Idempotent; also runs when the
        generator is garbage-collected before exhaustion."""
        if self._closed:
            return
        self._closed = True
        w = self._worker
        if not w.is_alive:
            return
        stream = self._stream
        try:
            if not w.store.is_ready(self._end_oid):
                stream.cancel()
                router = w.remote_router
                if router is not None and router.handles(self._end_oid):
                    router.cancel_stream(self._task_id)
                w.scheduler.cancel(self._task_id)
                # Materialize the typed cancellation end so any other
                # waiter (ray_tpu.wait on completed()) unblocks.
                w.store.cancel(self._end_oid, self._task_id)
            self._free_unconsumed()
        finally:
            self._release_stream()

    def _release_stream(self):
        self._worker.streams.pop(self._task_id)

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    def __repr__(self):
        return (f"ObjectRefGenerator({self._task_id.hex()[:16]}…, "
                f"next={self._index})")


class Worker:
    def __init__(self, num_cpus: Optional[int] = None,
                 num_tpus: Optional[int] = None,
                 resources: Optional[Dict[str, float]] = None,
                 session_dir: Optional[str] = None,
                 worker_mode: Optional[str] = None,
                 head_address: Optional[str] = None):
        self.is_alive = True
        # Control plane: with an address, this driver joins the standalone
        # head service (GCS analogue) — KV becomes cluster-global, named
        # actors resolve across drivers, objects pull across drivers.
        self.head_client = None
        self.remote_router = None
        if head_address:
            from ray_tpu._private.head_client import HeadClient

            self.head_client = HeadClient(head_address)
        self.job_id = JobID.from_int(os.getpid() & 0xFFFFFFFF)
        self.worker_id = WorkerID.from_random()
        self.node_id = NodeID.from_random()
        self.driver_task_id = TaskID.for_driver(self.job_id)
        self.session_dir = session_dir or os.path.join(
            tempfile.gettempdir(), "ray_tpu",
            f"session_{uuid.uuid4().hex[:12]}")
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        # Distributed tracing (RAY_TPU_TRACE): arm the per-process span
        # ring, and point spawned worker processes (env inherits) at
        # this session's trace dir so their spans surface through our
        # trace_dump. One `is None` branch everywhere when off.
        if os.environ.get(tracing.ENV_VAR):
            # Always re-point at OUR session (a daemon inherits the
            # launching driver's env): each runtime's child workers
            # spill locally, surfaced by this process's trace_dump.
            os.environ[tracing.ENV_DIR] = os.path.join(
                self.session_dir, "traces")
        tracer = tracing.install_from_env(component="driver")
        if tracer is not None and self.head_client is not None:
            # Node-qualify this process — and, via the env, its spawned
            # worker processes — so assembled views keep same-pid
            # processes on different hosts distinct.
            tracer.set_identity(node=self.head_client.client_id)
            os.environ[tracing.ENV_NODE] = self.head_client.client_id
        # Flight recorder (RAY_TPU_FLIGHT / RAY_TPU_PROFILE): same
        # arming shape as tracing — point spawned worker processes at
        # this session's flight dir so their spilled bundles surface
        # through this runtime's debug_dump. An OPERATOR-set
        # RAY_TPU_FLIGHT_DIR is authoritative and survives; only dirs
        # a ray_tpu runtime auto-pointed (marked by the _AUTO
        # sentinel, e.g. a daemon inheriting the launching driver's
        # session path — wrong host, wrong session) are re-pointed.
        from ray_tpu._private import flight

        if (os.environ.get(flight.ENV_VAR)
                or os.environ.get(flight.ENV_PROFILE)):
            if (not os.environ.get(flight.ENV_DIR)
                    or os.environ.get(flight.ENV_DIR_AUTO)):
                os.environ[flight.ENV_DIR] = os.path.join(
                    self.session_dir, "flight")
                os.environ[flight.ENV_DIR_AUTO] = "1"
        rec = flight.install_from_env(component="driver")
        if rec is not None:
            rec.dump_dir = os.environ.get(flight.ENV_DIR, rec.dump_dir)
            if self.head_client is not None:
                rec.set_identity(node=self.head_client.client_id)
                os.environ[flight.ENV_NODE] = self.head_client.client_id
        # session_latest convenience link (the `logs` CLI default target).
        link = os.path.join(os.path.dirname(self.session_dir),
                            "session_latest")
        try:
            if os.path.islink(link):
                os.unlink(link)
            os.symlink(self.session_dir, link)
        except OSError:
            pass
        self.serialization_context = SerializationContext()
        spill_dir = GlobalConfig.object_spill_dir or os.path.join(
            self.session_dir, "spill")
        self.store = ObjectStore(spill_dir)
        # Streaming-generator plane: per-task stream state (yield commit
        # counters, backpressure watermarks) for num_returns="streaming".
        from ray_tpu._private.streaming import StreamRegistry

        self.streams = StreamRegistry()
        self.task_events = TaskEventBuffer(GlobalConfig.task_events_max_buffer)
        if num_cpus is None:
            num_cpus = os.cpu_count() or 1
        total = {"CPU": float(num_cpus)}
        if num_tpus is None:
            try:
                import jax

                num_tpus = len([
                    d for d in jax.devices() if d.platform != "cpu"
                ])
            except Exception:  # noqa: BLE001 — jax optional at init
                num_tpus = 0
        if num_tpus:
            total["TPU"] = float(num_tpus)
        total.update(resources or {})
        self.resource_pool = ResourcePool(total)
        pool_size = GlobalConfig.worker_pool_size or max(int(num_cpus), 4)
        # Process execution plane: worker processes leased from a pool, fed
        # over the native shm store (reference: raylet WorkerPool + plasma).
        self.worker_mode = worker_mode or GlobalConfig.worker_mode
        self.shm_store = None
        self.worker_pool = None
        if self.worker_mode == "process":
            try:
                from ray_tpu._native.store import NativeObjectStore
                from ray_tpu._private.worker_pool import WorkerPool

                self.shm_store = NativeObjectStore.create(
                    capacity=GlobalConfig.shm_store_bytes,
                    max_objects=GlobalConfig.shm_store_slots)
                log_dir = os.path.join(self.session_dir, "logs")
                self.worker_pool = WorkerPool(
                    self.shm_store, num_workers=max(int(num_cpus), 1),
                    max_msg=GlobalConfig.worker_channel_bytes,
                    log_dir=log_dir)
                # Stream worker prints back to the driver (log plane).
                from ray_tpu._private.log_monitor import LogMonitor

                self.log_monitor = LogMonitor(log_dir)
            except Exception:  # noqa: BLE001 — no native toolchain: degrade
                # Release anything half-built: a created shm segment and
                # spawned worker processes must not outlive the fallback.
                if self.worker_pool is not None:
                    try:
                        self.worker_pool.shutdown()
                    except Exception:  # noqa: BLE001
                        pass
                if self.shm_store is not None:
                    try:
                        self.shm_store.close()
                    except Exception:  # noqa: BLE001
                        pass
                self.worker_mode = "thread"
                self.shm_store = None
                self.worker_pool = None
        self.scheduler = LocalScheduler(
            self.store, self.resource_pool, pool_size,
            task_events=self.task_events,
            worker_pool=self.worker_pool, shm_store=self.shm_store,
        )
        # Debug-mode host-plane sanitizer (RAY_TPU_SANITIZE=1): refcount
        # underflow + channel protocol checks hook in at their sites;
        # the stall watchdog needs the runtime handles.
        self.sanitizer_watchdog = None
        from ray_tpu.util import sanitizer as _sanitizer

        if _sanitizer.enabled():
            self.sanitizer_watchdog = _sanitizer.StallWatchdog(
                self.scheduler, self.resource_pool)
        # Flight-recorder section: scheduler/store depths render into
        # every local bundle (the "where is this process stuck" data).
        flight.add_section("runtime", self._flight_section)
        self.memory_monitor = None
        if (self.worker_pool is not None
                and GlobalConfig.memory_monitor_threshold > 0):
            from ray_tpu._private.memory_monitor import MemoryMonitor

            self.memory_monitor = MemoryMonitor(
                self.scheduler,
                threshold_fraction=GlobalConfig.memory_monitor_threshold)
        # Ownership plane: owners of refs borrowed FROM other drivers
        # (recorded at ref deserialization — serialized refs carry their
        # owner's identity + direct address).
        self.borrowed_owners: Dict[bytes, tuple] = {}
        self._borrowed_lock = threading.Lock()
        self.owner_resolver = None
        if self.head_client is not None:
            from ray_tpu._private.ownership import OwnerResolver
            from ray_tpu._private.remote_router import RemoteRouter

            self.remote_router = RemoteRouter(self)
            self.owner_resolver = OwnerResolver(self)
        self.submission_counter = _Counter()
        self.put_counter = _Counter()
        self.actor_counter = _Counter()
        self.actors: Dict[Any, Any] = {}  # ActorID -> _ActorRuntime
        self.named_actors: Dict[str, Any] = {}  # (namespace,name) -> handle
        self.placement_groups: Dict[Any, Any] = {}
        self._kv: Dict[bytes, bytes] = {}  # internal KV (GCS-KV parity)
        self._kv_lock = threading.Lock()
        if self.head_client is not None:
            # Head failover re-registration hook: when the client
            # observes a promoted head, this driver reconciles the
            # replayed directories with its live truth (named actors
            # it owns, cluster-actor placements it made).
            self.head_client.failover_callbacks.append(
                self._on_head_failover)

    def _on_head_failover(self, old_epoch: int, new_epoch: int) -> None:
        """Re-join announcements for a promoted head: re-register this
        driver's live named actors and re-place its live cluster
        actors. The promoted head replayed the shared log, so most
        entries already exist — re-registration by the same owner
        reconciles (overwrites) rather than conflicts, and entries
        lost in the dead primary's torn log tail reappear here."""
        hc = self.head_client
        if hc is None or not self.is_alive:
            return
        for (ns, name), handle in list(self.named_actors.items()):
            runtime = getattr(handle, "_runtime", None)
            if runtime is None or getattr(runtime, "dead", False):
                continue
            try:
                hc.actor_register(
                    ns, name, runtime.actor_id.binary(),
                    getattr(runtime, "class_name", "") or "")
            except Exception as exc:  # noqa: BLE001 — replayed entry
                log.warning("named-actor re-register of %r after "
                               "head failover failed (the replayed "
                               "directory entry still serves): %r",
                               name, exc)
        from ray_tpu._private.remote_actor import RemoteActorRuntime

        for runtime in list(self.actors.values()):
            if not isinstance(runtime, RemoteActorRuntime) \
                    or runtime.dead or runtime.borrower:
                continue
            try:
                hc.actor_place(runtime.actor_id.binary(), {
                    "node": runtime.node_client,
                    "driver": hc.client_id,
                    "cls": runtime._cls_bytes,
                    "class_name": runtime.class_name,
                    "detached":
                        runtime.opts.get("lifetime") == "detached",
                })
            except Exception as exc:  # noqa: BLE001 — same fallback
                log.warning("cluster-actor re-place after head "
                               "failover failed (replayed placement "
                               "still serves): %r", exc)

    def _flight_section(self) -> dict:
        """Runtime depths for this process's flight bundle: the
        queue/backlog numbers a postmortem reads first."""
        s = self.scheduler
        out = {
            "backlog": s.backlog_size(),
            "running": getattr(s, "num_running", lambda: 0)(),
            "finished": getattr(s, "num_finished", lambda: 0)(),
            "store_objects": len(getattr(self.store, "_entries", ())),
            "resources_available": self.resource_pool.available(),
            "worker_mode": self.worker_mode,
        }
        r = self.remote_router
        if r is not None:
            out["router"] = {
                "direct_pushes": getattr(r, "direct_pushes", 0),
                "relayed_pushes": getattr(r, "relayed_pushes", 0),
            }
        return out

    # ------------------------------------------------------------------- api
    def current_task_id(self) -> TaskID:
        tid = getattr(_task_context, "current_task_id", None)
        return tid if tid is not None else self.driver_task_id

    def next_task_id(self) -> TaskID:
        return TaskID.of(self.current_task_id(),
                         self.submission_counter.next())

    def put_object(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError(
                "Calling put() on an ObjectRef is not allowed; pass the ref "
                "directly instead.")
        oid = ObjectID.for_put(self.current_task_id(),
                               self.put_counter.next())
        serialized = self.serialization_context.serialize(value)
        self.store.put(oid, serialized)
        return ObjectRef(oid)

    def announce_object(self, ref: ObjectRef):
        """Publish this object's location to the head's object directory
        so other drivers can pull it (ObjectManager-relay analogue)."""
        if self.head_client is None:
            raise RayTpuError(
                "announce_object needs a head service "
                "(ray_tpu.init(address=...))")
        if not self.store.is_ready(ref.object_id):
            raise RayTpuError(
                "announce_object: the object is not materialized locally "
                "yet; ray_tpu.wait() on the ref first")
        self.head_client.object_announce(ref.object_id.binary())

    def _maybe_pull_from_head(self, object_id: ObjectID) -> None:
        """Cross-driver pull for objects with no local value and no known
        local producer. Refs of tasks this driver submitted resolve from
        lineage without a head round-trip; cross-driver refs (whether they
        arrived by pickle or were constructed from a hex id) pull once."""
        if self.head_client is None or self.store.is_ready(object_id):
            return
        if self.store.has_local_producer(object_id):
            return  # a local task/actor will produce it: never pullable
        if self.scheduler.lineage_for(object_id.task_id()) is not None:
            return  # a local task will produce it
        raw = self.head_client.object_pull(object_id.binary())
        if raw is not None:
            from ray_tpu._private.serialization import SerializedObject

            self.store.put(object_id, SerializedObject.from_bytes(raw))

    def record_borrowed_owner(self, oid_bin: bytes, owner_info):
        with self._borrowed_lock:
            if len(self.borrowed_owners) > 131072:
                # Hint table only (resolution falls back to the head):
                # recency-bounded via dict insertion order.
                self.borrowed_owners.pop(
                    next(iter(self.borrowed_owners)))
            self.borrowed_owners[oid_bin] = (
                owner_info[0], tuple(owner_info[1]))

    def borrowed_owner(self, oid_bin: bytes):
        with self._borrowed_lock:
            return self.borrowed_owners.get(oid_bin)

    def _pull_wait(self, object_id: ObjectID, timeout: Optional[float]):
        """Cross-driver resolve, event-driven end to end: a ref whose
        OWNER is known (serialized refs carry it) resolves/subscribes
        owner-direct over the p2p plane; an owner-less foreign ref (hex-
        constructed) subscribes to the head's ``obj|<hex>`` directory
        topic and re-pulls on announce — no poll loop either way. A
        typed ``GetTimeoutError`` materializes at the
        ``RAY_TPU_DEP_WAIT_S`` bound (or the caller's shorter timeout)."""
        import time as _time

        from ray_tpu.exceptions import GetTimeoutError

        if self.store.is_ready(object_id) or \
                self.store.has_local_producer(object_id) or \
                self.scheduler.lineage_for(object_id.task_id()) is not None:
            return  # locally produced: the plain store wait covers it
        # An EXPLICIT caller timeout is the contract — longer or shorter
        # than the default wait bound; dep_wait_s only bounds the
        # unbounded (timeout=None) case.
        bound = float(GlobalConfig.dep_wait_s) if timeout is None \
            else float(timeout)
        deadline = _time.monotonic() + bound
        owner = self.borrowed_owner(object_id.binary())
        if owner is not None and self.owner_resolver is not None:
            self.owner_resolver.resolve(
                object_id.binary(), owner[1], owner[0], deadline=deadline)
            return
        # Owner unknown: head fallback directory. Subscribe BEFORE the
        # first pull so an announce landing in between still wakes us.
        import queue as _queue

        sub = None
        try:
            try:
                sub = self.head_client.subscribe(
                    "obj|" + object_id.binary().hex())
            except Exception:  # noqa: BLE001 — head hiccup: the bounded
                sub = None     # store waits below degrade gracefully
            while True:
                self._maybe_pull_from_head(object_id)
                if self.store.is_ready(object_id) or \
                        self.store.has_local_producer(object_id):
                    return
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    raise GetTimeoutError(
                        f"foreign object {object_id.hex()[:16]}… was "
                        f"never announced/resolvable within "
                        f"{bound:.0f}s (RAY_TPU_DEP_WAIT_S)")
                if sub is not None:
                    try:
                        sub.get(timeout=min(remaining, 5.0))
                    except _queue.Empty:
                        pass  # deadline re-check; no announce yet
                else:
                    self.store.wait([object_id], 1, min(remaining, 0.25))
        finally:
            if sub is not None:
                try:
                    sub.close()
                except Exception:  # noqa: BLE001 — head gone
                    pass

    def get_object(self, ref: ObjectRef, timeout: Optional[float] = None):
        router = self.remote_router
        if router is not None and not self.store.is_ready(ref.object_id) \
                and router.handles(ref.object_id):
            router.ensure_local(ref.object_id, timeout=timeout)
        elif self.head_client is not None:
            self._pull_wait(ref.object_id, timeout)
        if self.store.is_lost(ref.object_id):
            # Lineage reconstruction (cluster mode): re-execute producers.
            cluster = getattr(self, "cluster", None)
            if cluster is not None and cluster.recover_object(ref.object_id):
                self.store.clear_lost(ref.object_id)
            else:
                from ray_tpu.exceptions import ObjectLostError

                raise ObjectLostError(
                    f"object {ref.object_id.hex()[:16]}… lost and no "
                    f"lineage is available to reconstruct it")
        serialized = self.store.get(ref.object_id, timeout=timeout)
        value = self.serialization_context.deserialize(serialized)
        if isinstance(value, RayTaskError):
            raise value.as_instanceof_cause()
        return value

    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        # Pin args that are refs for the duration of the task (submitted-refs
        # in the reference's refcount protocol).
        from ray_tpu._private.scheduler import _collect_refs

        if tracing._TRACER is not None and spec.trace is None:
            # Capture the submitting thread's ambient context: local
            # execution bridges spans off task events; routed execution
            # ships it inside the task payload.
            spec.trace = tracing.inject()
            if spec.trace is not None:
                tracing.register_task(spec.task_id.binary(), spec.trace)

        dep_refs = _collect_refs(spec.args, spec.kwargs)
        for ref in dep_refs:
            self.store.add_submitted_ref(ref.object_id)
        cluster = getattr(self, "cluster", None)
        routed = (cluster is None and self.remote_router is not None
                  and self.remote_router.maybe_route(spec))
        if not routed and getattr(self, "client_mode", False):
            # Thin clients never execute locally — zero-resource tasks
            # included; an unroutable task fails loudly instead of
            # queueing against capacity that will never exist here.
            for ref in dep_refs:  # undo the submitted-ref pins
                self.store.remove_submitted_ref(ref.object_id)
            raise RayTpuError(
                "client-mode driver (ray://) has no local execution "
                "capacity and no feasible cluster node accepted the task "
                "— start node daemons with `ray-tpu start --address=`")
        if not routed:
            # Remote results have no local producer — their bytes arrive
            # by head-relayed pull, which a producer mark would suppress.
            for oid in spec.return_ids:
                self.store.mark_local_producer(oid)
        refs = [ObjectRef(oid) for oid in spec.return_ids]
        if dep_refs:
            def _release(_refs=dep_refs):
                for r in _refs:
                    self.store.remove_submitted_ref(r.object_id)
            self.store.on_ready(spec.return_ids[0], _release)
        if routed:
            pass  # the router owns dispatch + completion
        elif cluster is not None:
            cluster.submit(spec)
        else:
            self.scheduler.submit(spec)
        return refs

    def wait(self, object_ids: List[ObjectID], num_returns: int,
             timeout: Optional[float]):
        router = self.remote_router
        if router is not None:
            # Completed-but-unpulled remote results count as ready only
            # once local; fetch them in the background so wait() observes
            # completion promptly.
            for oid in object_ids:
                if router.handles(oid) and not self.store.is_ready(oid):
                    router.prefetch(oid)
        if self.head_client is not None:
            for oid in object_ids:
                if self.store.is_ready(oid):
                    continue
                owner = self.borrowed_owner(oid.binary())
                if owner is not None and self.owner_resolver is not None:
                    # Borrowed ref: resolve through its OWNER in the
                    # background (deduped) — the head's directory never
                    # saw this object.
                    self.owner_resolver.prefetch(oid.binary(), owner)
                else:
                    self._maybe_pull_from_head(oid)
        return self.store.wait(object_ids, num_returns, timeout)

    # -------------------------------------------------------- internal KV ---
    # With a head attached the KV is cluster-global (GCS-KV semantics);
    # standalone it is driver-local.
    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True) -> bool:
        if self.head_client is not None:
            return self.head_client.kv_put(key, value, overwrite)
        with self._kv_lock:
            if not overwrite and key in self._kv:
                return False
            self._kv[key] = value
            return True

    def kv_get(self, key: bytes) -> Optional[bytes]:
        if self.head_client is not None:
            return self.head_client.kv_get(key)
        with self._kv_lock:
            return self._kv.get(key)

    def kv_del(self, key: bytes) -> bool:
        if self.head_client is not None:
            return self.head_client.kv_del(key)
        with self._kv_lock:
            return self._kv.pop(key, None) is not None

    def kv_keys(self, prefix: bytes = b"") -> List[bytes]:
        if self.head_client is not None:
            return self.head_client.kv_keys(prefix)
        with self._kv_lock:
            return [k for k in self._kv if k.startswith(prefix)]

    def shutdown(self):
        self.is_alive = False
        actors = list(self.actors.values())
        for actor in actors:
            if getattr(actor, "borrower", False):
                continue  # not ours to kill: the owning driver decides
            try:
                actor.terminate(no_restart=True)
            except Exception:  # noqa: BLE001
                pass
        for actor in actors:
            # Join the loop threads BEFORE the shm store unmaps: a
            # process-actor loop tears its channels down on _TERMINATE and
            # must not race the munmap.
            try:
                actor.join(timeout=2)
            except Exception:  # noqa: BLE001
                pass
        self.actors.clear()
        self.named_actors.clear()
        if self.memory_monitor is not None:
            self.memory_monitor.stop()
            self.memory_monitor = None
        if self.sanitizer_watchdog is not None:
            self.sanitizer_watchdog.stop()
            self.sanitizer_watchdog = None
        self.scheduler.shutdown()
        if self.worker_pool is not None:
            self.worker_pool.shutdown()
            self.worker_pool = None
        if getattr(self, "log_monitor", None) is not None:
            self.log_monitor.stop()
            self.log_monitor = None
        if self.remote_router is not None:
            self.remote_router.shutdown()
            self.remote_router = None
        if self.head_client is not None:
            self.head_client.close()
            self.head_client = None
        if self.shm_store is not None:
            self.shm_store.close()
            self.shm_store = None


_global_worker: Optional[Worker] = None
_init_lock = threading.Lock()


def _try_global_worker() -> Optional[Worker]:
    return _global_worker


def try_live_worker() -> Optional[Worker]:
    """The global worker iff one is up AND alive — the runtime-discovery
    check the KV-backed planes (memory:// filesystem, workflow journal)
    share."""
    w = _global_worker
    return w if w is not None and w.is_alive else None


def global_worker() -> Worker:
    if _global_worker is None:
        raise RayTpuError(
            "ray_tpu has not been initialized; call ray_tpu.init() first "
            "(or use auto-init by calling a remote function)."
        )
    return _global_worker


def is_initialized() -> bool:
    return _global_worker is not None


def init(num_cpus: Optional[int] = None, num_tpus: Optional[int] = None,
         resources: Optional[Dict[str, float]] = None,
         _system_config: Optional[Dict[str, Any]] = None,
         ignore_reinit_error: bool = False, namespace: str = "default",
         worker_mode: Optional[str] = None,
         address: Optional[str] = None,
         **_ignored) -> "Worker":
    global _global_worker
    with _init_lock:
        if _global_worker is not None:
            if ignore_reinit_error:
                return _global_worker
            raise RayTpuError(
                "ray_tpu.init() called twice; pass ignore_reinit_error=True "
                "to allow.")
        if _system_config:
            GlobalConfig.apply_system_config(_system_config)
        if address in ("auto", "local"):
            from ray_tpu._private.head_service import DEFAULT_PORT

            address = f"127.0.0.1:{DEFAULT_PORT}"
        client_mode = bool(address) and address.startswith("ray://")
        if client_mode:
            # Ray-Client role: a THIN attach — this process keeps no task
            # execution capacity (num_cpus=0, no process pool); every
            # .remote() routes onto the cluster's node daemons through
            # the head, and results pull back on demand. Actors created
            # here still live in this process (cross-driver named actors
            # resolve cluster-wide as usual).
            address = address[len("ray://"):]
            num_cpus = 0
            num_tpus = 0
            resources = {}
            worker_mode = worker_mode or "thread"
        _global_worker = Worker(num_cpus=num_cpus, num_tpus=num_tpus,
                                resources=resources,
                                worker_mode=worker_mode,
                                head_address=address)
        _global_worker.client_mode = client_mode
        _global_worker.namespace = namespace
        atexit.register(shutdown)
        return _global_worker


def shutdown():
    global _global_worker
    with _init_lock:
        if _global_worker is None:
            return
        _global_worker.shutdown()
        _global_worker = None


def auto_init() -> Worker:
    if _global_worker is None:
        init(ignore_reinit_error=True)
    return _global_worker


# ------------------------------------------------------------ public verbs --
def put(value: Any) -> ObjectRef:
    return auto_init().put_object(value)


def get(refs: Union[ObjectRef, List[ObjectRef]],
        *, timeout: Optional[float] = None):
    worker = auto_init()
    if isinstance(refs, ObjectRef):
        return worker.get_object(refs, timeout=timeout)
    if not isinstance(refs, list):
        raise TypeError(
            f"get() expects an ObjectRef or list of ObjectRefs, got "
            f"{type(refs)}")
    # Pipelined result prefetch: kick off background pulls for every
    # remote-routed ref up front so the sequential get loop below finds
    # most bytes already local instead of paying one pull RTT per ref.
    router = worker.remote_router
    if router is not None:
        for r in refs:
            if worker.store.is_ready(r.object_id):
                continue
            if router.handles(r.object_id):
                router.prefetch(r.object_id)
            else:
                owner = worker.borrowed_owner(r.object_id.binary())
                if owner is not None and \
                        worker.owner_resolver is not None:
                    worker.owner_resolver.prefetch(
                        r.object_id.binary(), owner)
    # One overall deadline across the whole list, not per ref.
    import time as _time

    deadline = None if timeout is None else _time.monotonic() + timeout
    out = []
    for r in refs:
        remaining = None
        if deadline is not None:
            remaining = max(deadline - _time.monotonic(), 0.0)
        out.append(worker.get_object(r, timeout=remaining))
    return out


def wait(refs: List[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    worker = auto_init()
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    if len(set(refs)) != len(refs):
        raise ValueError("wait() expects a list of unique ObjectRefs")
    if num_returns > len(refs):
        raise ValueError(
            f"num_returns ({num_returns}) exceeds number of refs "
            f"({len(refs)})")
    ready_ids, not_ready_ids = worker.wait(
        [r.object_id for r in refs], num_returns, timeout)
    by_id = {r.object_id: r for r in refs}
    return ([by_id[i] for i in ready_ids], [by_id[i] for i in not_ready_ids])


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    worker = global_worker()
    task_id = ref.object_id.task_id()
    removed = worker.scheduler.cancel(task_id, force=force)
    if removed or force:
        worker.store.cancel(ref.object_id, task_id)
