"""Streaming-generator plane: per-task stream state + registry.

Rebuild of the reference's streaming generator machinery (reference:
``ObjectRefGenerator`` in python/ray/_raylet.pyx plus the core-worker
task-manager streaming protocol [unverified]). A ``num_returns="streaming"``
task commits one object per yield — ``ObjectID.for_task_return(task_id, i)``,
dynamically created return refs derived from the task id exactly like
static returns, so lineage reconstruction re-derives the same ids and a
replayed generator re-commits already-consumed indices idempotently.

One ``StreamState`` per task tracks the stream on WHICHEVER runtime hosts
the role:

- the PRODUCER runtime (driver thread plane, worker process, node daemon)
  counts committed yields and pauses the yield loop when
  committed-but-unconsumed items reach the backpressure budget
  (``RAY_TPU_GENERATOR_BACKPRESSURE_ITEMS``);
- the CONSUMER runtime (the driver owning the ``ObjectRefGenerator``)
  counts consumption at ``next()`` and fires ack callbacks that propagate
  the consumed watermark back to the producer. In-process both roles share
  ONE instance; across a worker-process boundary acks ride the stream-ack
  channel; across nodes they ride ``item_ack`` on the direct plane.

End-of-stream is itself an object: the STREAM END MARKER
(``ObjectID.for_task_return(task_id, STREAM_END_INDEX)``) commits the total
item count when the generator finishes — or the task's error — so the
whole existing completion machinery (submitted-ref release, ``task_done``
reporting, typed error materialization, ``ray_tpu.wait``) applies to
streaming tasks unchanged.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from ray_tpu._private.ids import STREAM_END_INDEX, ObjectID, TaskID

__all__ = ["STREAM_END_INDEX", "StreamState", "StreamRegistry",
           "stream_item_id", "stream_end_id"]


def stream_item_id(task_id: TaskID, index: int) -> ObjectID:
    """The dynamically-created return ref of one yield."""
    if index >= STREAM_END_INDEX:
        raise ValueError(
            f"streaming generator yielded more than {STREAM_END_INDEX} "
            f"items (index space exhausted)")
    return ObjectID.for_task_return(task_id, index)


def stream_end_id(task_id: TaskID) -> ObjectID:
    return ObjectID.for_task_return(task_id, STREAM_END_INDEX)


class StreamState:
    """Producer/consumer bookkeeping for one streaming-generator task."""

    __slots__ = ("task_id", "_cv", "committed", "consumed", "finished",
                 "error", "cancelled", "peak_unconsumed", "paused_events",
                 "_commit_cbs", "_consume_cbs", "known_remote_sizes")

    def __init__(self, task_id: TaskID):
        self.task_id = task_id
        self._cv = threading.Condition()
        self.committed = 0          # contiguous commit count (producer side)
        self.consumed = 0           # consumer watermark (next() returns)
        self.finished: Optional[int] = None  # total items once producer ends
        self.error: Optional[BaseException] = None
        self.cancelled = False
        # Telemetry proved in tests/bench: the max committed-but-unconsumed
        # count this producer ever reached, and how often it paused.
        self.peak_unconsumed = 0
        self.paused_events = 0
        self._commit_cbs: List[Callable[[int, ObjectID], None]] = []
        self._consume_cbs: List[Callable[[int], None]] = []
        # Consumer side: item index -> byte size for items whose bytes
        # stayed on the producing node (announce + pull, not inlined).
        self.known_remote_sizes: Dict[int, int] = {}

    # ------------------------------------------------------------- producer
    def commit(self, index: int):
        """One yield's object is in the store (in index order)."""
        with self._cv:
            if index + 1 > self.committed:
                self.committed = index + 1
            gap = self.committed - self.consumed
            if gap > self.peak_unconsumed:
                self.peak_unconsumed = gap
            cbs = list(self._commit_cbs)
            self._cv.notify_all()
        oid = stream_item_id(self.task_id, index)
        for cb in cbs:  # outside the lock: listeners take their own locks
            cb(index, oid)

    def wait_capacity(self, budget: int,
                      cancel_event: Optional[threading.Event] = None,
                      poll_s: float = 0.1) -> bool:
        """Producer pause point: block while committed-but-unconsumed items
        have reached ``budget`` (0 = unlimited). Returns False when the
        stream was cancelled (the yield loop should stop)."""
        if budget <= 0:
            return not self.cancelled
        first = True
        with self._cv:
            while (self.committed - self.consumed >= budget
                   and not self.cancelled):
                if cancel_event is not None and cancel_event.is_set():
                    return False
                if first:
                    self.paused_events += 1
                    first = False
                # The cv wakes on advance_consumed/cancel; the bounded
                # wait only covers an external cancel_event flip.
                self._cv.wait(poll_s)
            return not self.cancelled

    def finish(self, total: int):
        with self._cv:
            self.finished = total
            self._cv.notify_all()

    def set_error(self, exc: BaseException):
        with self._cv:
            if self.error is None:
                self.error = exc
            self._cv.notify_all()

    def cancel(self):
        with self._cv:
            self.cancelled = True
            self._cv.notify_all()

    # ------------------------------------------------------------- consumer
    def advance_consumed(self, n: int):
        """Consumption watermark moved to ``n`` (monotonic). On the
        consumer runtime this fires the ack listeners (wire propagation);
        on the producer runtime it wakes the paused yield loop — in
        process-local streams both happen on the same instance."""
        with self._cv:
            if n <= self.consumed:
                return
            self.consumed = n
            cbs = list(self._consume_cbs)
            self._cv.notify_all()
        for cb in cbs:
            cb(n)

    def unconsumed(self) -> int:
        with self._cv:
            return self.committed - self.consumed

    # ------------------------------------------------------------ listeners
    def add_commit_listener(self, cb: Callable[[int, ObjectID], None]):
        with self._cv:
            self._commit_cbs.append(cb)

    def add_consume_listener(self, cb: Callable[[int], None]):
        with self._cv:
            self._consume_cbs.append(cb)


class StreamRegistry:
    """task_id -> StreamState table on a runtime (driver or node)."""

    def __init__(self):
        self._streams: Dict[TaskID, StreamState] = {}
        self._lock = threading.Lock()

    def get_or_create(self, task_id: TaskID) -> StreamState:
        with self._lock:
            st = self._streams.get(task_id)
            if st is None:
                st = self._streams[task_id] = StreamState(task_id)
            return st

    def get(self, task_id: TaskID) -> Optional[StreamState]:
        with self._lock:
            return self._streams.get(task_id)

    def pop(self, task_id: TaskID) -> Optional[StreamState]:
        with self._lock:
            return self._streams.pop(task_id, None)

    def __len__(self):
        with self._lock:
            return len(self._streams)
