"""Task state-event ring buffer powering the state API and timeline.

Rebuild of the reference's task event pipeline (core worker task_event_buffer
→ GCS task manager ring buffer [unverified]): every task records status
transitions with timestamps into a bounded ring; the state API lists/queries
them and the timeline exporter emits Chrome-tracing JSON.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class TaskEvent:
    task_id: object
    state: str
    timestamp: float
    name: str = ""
    duration: Optional[float] = None
    extra: dict = field(default_factory=dict)


class TaskEventBuffer:
    def __init__(self, capacity: int = 100_000):
        self._events = collections.deque(maxlen=capacity)
        self._latest_state: Dict[object, TaskEvent] = {}
        self._lock = threading.Lock()

    def record(self, task_id, state: str, name: str = "",
               duration: Optional[float] = None, **extra):
        ev = TaskEvent(task_id, state, time.time(), name, duration, extra)
        with self._lock:
            self._events.append(ev)
            self._latest_state[task_id] = ev
            if len(self._latest_state) > self._events.maxlen:
                # Trim finished entries to bound the index.
                for tid in list(self._latest_state)[: 1000]:
                    if self._latest_state[tid].state in (
                        "FINISHED", "FAILED"
                    ):
                        del self._latest_state[tid]

    def list_events(self, limit: int = 10_000) -> List[TaskEvent]:
        with self._lock:
            return list(self._events)[-limit:]

    def list_tasks(self, state: Optional[str] = None,
                   limit: int = 10_000) -> List[TaskEvent]:
        with self._lock:
            out = [
                ev for ev in self._latest_state.values()
                if state is None or ev.state == state
            ]
        return out[:limit]

    def summary(self) -> Dict[str, int]:
        with self._lock:
            counts: Dict[str, int] = {}
            for ev in self._latest_state.values():
                counts[ev.state] = counts.get(ev.state, 0) + 1
            return counts

    def to_chrome_trace(self) -> List[dict]:
        """Chrome-tracing JSON events (`ray timeline` parity)."""
        events = self.list_events()
        trace = []
        starts: Dict[object, TaskEvent] = {}
        for ev in events:
            if ev.state == "RUNNING":
                starts[ev.task_id] = ev
            elif ev.state in ("FINISHED", "FAILED"):
                st = starts.pop(ev.task_id, None)
                if st is not None:
                    trace.append({
                        "name": ev.name or "task",
                        "cat": "task",
                        "ph": "X",
                        "ts": st.timestamp * 1e6,
                        "dur": max((ev.timestamp - st.timestamp) * 1e6, 1),
                        "pid": 0,
                        "tid": 0,
                        "args": {"state": ev.state},
                    })
        return trace
