"""Task state-event ring buffer powering the state API and timeline.

Rebuild of the reference's task event pipeline (core worker task_event_buffer
→ GCS task manager ring buffer [unverified]): every task records status
transitions with timestamps into a bounded ring; the state API lists/queries
them and the timeline exporter emits Chrome-tracing JSON. Node daemons ship
their rings home piggybacked on completion-report batches (``ingest``), so
a driver's ``util.state.list_tasks()`` sees cluster tasks without any new
steady-state head RPCs. When tracing is armed, every recorded transition
also bridges into ``_private/tracing.py`` spans (time spent per state).
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from ray_tpu._private import tracing

_TERMINAL = ("FINISHED", "FAILED")


@dataclass
class TaskEvent:
    task_id: object
    state: str
    timestamp: float
    name: str = ""
    duration: Optional[float] = None
    extra: dict = field(default_factory=dict)


class TaskEventBuffer:
    def __init__(self, capacity: int = 100_000):
        self._events = collections.deque(maxlen=capacity)
        self._latest_state: Dict[object, TaskEvent] = {}
        # Deterministic terminal-state eviction: task ids whose latest
        # state is terminal, oldest first. Bounded at the ring capacity,
        # so the index holds at most (live tasks + capacity) entries —
        # the old threshold-scan path could retain stale terminal
        # states unboundedly ahead of the ring under churn.
        self._terminal_order: "collections.deque" = collections.deque()
        self._seq = 0  # monotonic event counter (node->driver shipping)
        self._lock = threading.Lock()

    def record(self, task_id, state: str, name: str = "",
               duration: Optional[float] = None, **extra):
        ev = TaskEvent(task_id, state, time.time(), name, duration, extra)
        traced = tracing._TRACER is not None
        with self._lock:
            self._append_locked(ev)
            prev = self._latest_state.get(task_id) if traced else None
            self._latest_state[task_id] = ev
            if state in _TERMINAL:
                self._terminal_order.append(task_id)
                self._evict_terminal_locked()
        if traced:
            tracing.on_task_event(task_id, state, name, prev)

    def _append_locked(self, ev: TaskEvent):
        self._events.append(ev)
        self._seq += 1
        ev.extra.setdefault("_seq", self._seq)

    def _evict_terminal_locked(self):
        # Evict on terminal RECORD, oldest terminal first. A task that
        # re-ran after finishing (lineage replay) re-enters
        # _terminal_order on its next terminal record, so dropping a
        # stale marker whose task is live again is safe.
        while len(self._terminal_order) > self._events.maxlen:
            tid = self._terminal_order.popleft()
            latest = self._latest_state.get(tid)
            if latest is not None and latest.state in _TERMINAL:
                del self._latest_state[tid]

    def ingest(self, events: Iterable[Tuple]) -> int:
        """Merge events shipped from another process (a node daemon's
        ring riding its completion-report batches): tuples of
        ``(task_id, state, timestamp, name, duration, node)``. Original
        timestamps are preserved; the source node lands in ``extra``."""
        count = 0
        with self._lock:
            for task_id, state, ts, name, duration, node in events:
                ev = TaskEvent(task_id, state, float(ts), name, duration,
                               {"node": node})
                self._append_locked(ev)
                prev = self._latest_state.get(task_id)
                # Last-writer-wins by ORIGINAL timestamp within a state
                # class, but terminal beats non-terminal outright: the
                # shipping node's clock may trail this process's (NTP
                # skew), and a FINISHED stamped "earlier" than the local
                # PENDING record must still land — and a stale replayed
                # RUNNING must never regress a terminal state.
                prev_terminal = (prev is not None
                                 and prev.state in _TERMINAL)
                new_terminal = state in _TERMINAL
                if prev is None or (new_terminal and not prev_terminal):
                    take = True
                elif prev_terminal and not new_terminal:
                    take = False
                else:
                    take = prev.timestamp <= ev.timestamp
                if take:
                    self._latest_state[task_id] = ev
                    if new_terminal and not prev_terminal:
                        self._terminal_order.append(task_id)
                        self._evict_terminal_locked()
                count += 1
        # No tracing bridge here: the recording process already emitted
        # spans for these transitions into ITS ring — re-bridging would
        # duplicate every span in the assembled trace.
        return count

    def drain_since(self, cursor: int, limit: int = 4096
                    ) -> Tuple[int, List[TaskEvent]]:
        """Events recorded after ``cursor`` (a sequence number from a
        previous call), newest-bounded: the node daemon's reporter
        piggybacks these onto its coalesced completion batches. Returns
        ``(new_cursor, events)``; O(new events), not O(ring)."""
        with self._lock:
            if self._seq <= cursor:
                return self._seq, []
            fresh: List[TaskEvent] = []
            for ev in reversed(self._events):
                if ev.extra.get("_seq", 0) <= cursor:
                    break
                fresh.append(ev)
            fresh.reverse()
            if len(fresh) > limit:
                # Truncate from the FRONT but advance the cursor only
                # to the last shipped event, so the rest ship next
                # flush instead of being silently skipped.
                fresh = fresh[:limit]
            new_cursor = fresh[-1].extra["_seq"] if fresh else self._seq
            return new_cursor, fresh

    def index_size(self) -> int:
        with self._lock:
            return len(self._latest_state)

    def latest_seq(self) -> int:
        with self._lock:
            return self._seq

    def list_events(self, limit: int = 10_000) -> List[TaskEvent]:
        with self._lock:
            return list(self._events)[-limit:]

    def list_tasks(self, state: Optional[str] = None,
                   limit: int = 10_000) -> List[TaskEvent]:
        with self._lock:
            out = [
                ev for ev in self._latest_state.values()
                if state is None or ev.state == state
            ]
        return out[:limit]

    def summary(self) -> Dict[str, int]:
        with self._lock:
            counts: Dict[str, int] = {}
            for ev in self._latest_state.values():
                counts[ev.state] = counts.get(ev.state, 0) + 1
            return counts

    def to_chrome_trace(self) -> List[dict]:
        """Chrome-tracing JSON events (`ray timeline` parity)."""
        events = self.list_events()
        trace = []
        starts: Dict[object, TaskEvent] = {}
        for ev in events:
            if ev.state == "RUNNING":
                starts[ev.task_id] = ev
            elif ev.state in _TERMINAL:
                st = starts.pop(ev.task_id, None)
                if st is not None:
                    trace.append({
                        "name": ev.name or "task",
                        "cat": "task",
                        "ph": "X",
                        "ts": st.timestamp * 1e6,
                        "dur": max((ev.timestamp - st.timestamp) * 1e6, 1),
                        "pid": 0,
                        "tid": 0,
                        "args": {"state": ev.state,
                                 "node": ev.extra.get("node", "")},
                    })
        return trace
