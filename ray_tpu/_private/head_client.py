"""Driver-side connection to the head service (GCS client analogue).

Each attached driver keeps two connections to the head process: a request
channel for its own RPCs (KV, directories, relayed calls) and an event
channel the head pushes work through — relayed actor calls from OTHER
drivers and object pulls — served by a daemon thread against the local
runtime. A heartbeat thread keeps the membership entry alive; silence
past the head's timeout marks this driver dead and garbage-collects its
directory entries (failure detection).
"""

from __future__ import annotations

import pickle
import threading
import uuid
from multiprocessing.connection import Client as _Connect
from typing import Any, Optional, Tuple

from ray_tpu._private.head_service import AUTHKEY


def parse_address(address: str) -> Tuple[str, int]:
    host, _, port = address.rpartition(":")
    return host or "127.0.0.1", int(port)


class HeadClient:
    def __init__(self, address: str,
                 client_id: Optional[str] = None):
        self.address = parse_address(address)
        self.client_id = client_id or f"driver-{uuid.uuid4().hex[:8]}"
        self._req = _Connect(self.address, authkey=AUTHKEY)
        self._req.send(("hello", self.client_id, "request"))
        self._check(self._req.recv())
        self._event = _Connect(self.address, authkey=AUTHKEY)
        self._event.send(("hello", self.client_id, "event"))
        self._check(self._event.recv())
        # Dedicated heartbeat connection: a long relayed RPC on the
        # request channel must not starve liveness (the head would mark
        # this driver dead mid-call and GC its directory entries).
        self._hb = _Connect(self.address, authkey=AUTHKEY)
        self._hb.send(("hello", self.client_id, "request"))
        self._check(self._hb.recv())
        self._hb_lock = threading.Lock()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._event_thread = threading.Thread(
            target=self._event_loop, daemon=True,
            name="ray_tpu_head_events")
        self._event_thread.start()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name="ray_tpu_head_heartbeat")
        self._hb_thread.start()

    @staticmethod
    def _check(reply):
        status, value = reply
        if status == "err":
            raise value
        return value

    def _request(self, msg: tuple):
        with self._lock:
            self._req.send(msg)
            return self._check(self._req.recv())

    # ------------------------------------------------------------------ kv
    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True):
        return self._request(("kv_put", key, value, overwrite))

    def kv_get(self, key: bytes):
        return self._request(("kv_get", key))

    def kv_del(self, key: bytes):
        return self._request(("kv_del", key))

    def kv_keys(self, prefix: bytes = b""):
        return self._request(("kv_keys", prefix))

    # -------------------------------------------------------------- actors
    def actor_register(self, namespace: str, name: str, actor_bin: bytes,
                       class_name: str):
        return self._request(
            ("actor_register", namespace, name, actor_bin, class_name))

    def actor_lookup(self, namespace: str, name: str):
        return self._request(("actor_lookup", namespace, name))

    def actor_deregister(self, namespace: str, name: str):
        return self._request(("actor_deregister", namespace, name))

    def actor_call(self, owner_id: str, actor_bin: bytes, method: str,
                   args, kwargs, num_returns: int):
        value = self._request((
            "actor_call", owner_id, actor_bin, method,
            pickle.dumps((args, kwargs), protocol=5), num_returns))
        return pickle.loads(value)  # serialized results (or raises)

    # ------------------------------------------------------------- objects
    def object_announce(self, oid_bin: bytes):
        return self._request(("object_announce", oid_bin))

    def object_pull(self, oid_bin: bytes):
        return self._request(("object_pull", oid_bin))

    def cluster_info(self) -> dict:
        return self._request(("cluster_info",))

    # -------------------------------------------------------------- events
    def _event_loop(self):
        """Serve relayed work from other drivers against the local
        runtime (the per-node agent role). A dropped event channel (the
        head pruned us while frozen) reconnects with a fresh hello, so
        relays to this driver resume after revival."""
        from ray_tpu._private import worker as worker_mod

        while not self._stop.is_set():
            try:
                msg = self._event.recv()
            except (EOFError, OSError):
                if self._stop.is_set():
                    return
                try:
                    self._event = _Connect(self.address, authkey=AUTHKEY)
                    self._event.send(("hello", self.client_id, "event"))
                    self._check(self._event.recv())
                    continue
                except Exception:  # noqa: BLE001 — head gone for real
                    return
            try:
                reply = ("ok", self._handle_event(worker_mod, msg))
            except Exception as exc:  # noqa: BLE001 — event boundary
                reply = ("err", exc)
            try:
                self._event.send(reply)
            except (EOFError, OSError):
                return
            except Exception:  # noqa: BLE001 — unpicklable error payload:
                # MUST still reply or the head's relay blocks forever
                # holding this owner's event lock.
                try:
                    self._event.send(("err", RuntimeError(
                        f"unpicklable event reply: {reply!r:.200}")))
                except (EOFError, OSError):
                    return

    def _handle_event(self, worker_mod, msg: tuple):
        kind = msg[0]
        w = worker_mod._try_global_worker()
        if w is None or not w.is_alive:
            raise RuntimeError("driver runtime is down")
        if kind == "actor_call":
            _, actor_bin, method, args_bytes, num_returns = msg
            from ray_tpu._private.ids import ActorID

            runtime = w.actors.get(ActorID(actor_bin))
            if runtime is None:
                raise ValueError("actor no longer exists on its owner")
            args, kwargs = pickle.loads(args_bytes)
            refs = runtime.submit(method, args, kwargs, num_returns,
                                  method)
            # Resolve results locally; cross-driver handles get VALUES
            # back (one round trip), not refs into a foreign store.
            import ray_tpu

            values = [ray_tpu.get(r, timeout=60.0) for r in refs]
            return pickle.dumps(values, protocol=5)
        if kind == "object_get":
            _, oid_bin = msg
            from ray_tpu._private.ids import ObjectID

            serialized = w.store.get(ObjectID(oid_bin), timeout=30.0)
            return serialized.to_bytes()
        raise ValueError(f"unknown event {kind!r}")

    def _heartbeat_loop(self):
        while not self._stop.wait(0.5):
            try:
                with self._hb_lock:
                    self._hb.send(("heartbeat",))
                    self._check(self._hb.recv())
            except Exception:  # noqa: BLE001 — head gone
                return

    def close(self):
        self._stop.set()
        for conn in (self._req, self._event, self._hb):
            try:
                conn.close()
            except OSError:
                pass
